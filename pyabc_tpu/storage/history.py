"""History: durable generation-by-generation storage + resume.

Parity: pyabc/storage/history.py (1799 LoC) + the ORM schema
pyabc/storage/db_model.py:35-127 (ABCSMC -> Population -> Model -> Particle
-> Parameter/Sample/SummaryStatistic).

TPU re-design: the reference's row-per-particle ORM insert
(history.py:617-693) is a known bottleneck at large N (SURVEY.md §7 hard
part "DB write throughput at 1e6 particles/generation").  Here each
(population, model) stores its particles as *array blobs* (float32
theta/weight/distance matrices + the flattened sum-stat block) in stdlib
sqlite3 — one INSERT per model per generation regardless of N, written
straight from device arrays.  Row-level access for analysis/export is
reconstructed on read (``get_distribution`` returns a pandas DataFrame like
the reference's, history.py:269-330).

The observed data, per-generation ε, sample counts and component configs
are stored for full ``ABCSMC.load`` resume parity (reference
smc.py:355-389; every generation is durable before the next starts,
smc.py:921 / SURVEY.md §5.4).
"""

from __future__ import annotations

import datetime
import io
import json
import logging
import os
import sqlite3
import time
import zlib
from typing import Dict, List, Optional, Tuple, Union

import jax.numpy as jnp
import numpy as np
import pandas as pd

from ..population import Population
from .bytes_storage import from_bytes, to_bytes

logger = logging.getLogger("ABC.History")

PRE_TIME = -1  # calibration-sample time index (reference history.py:135)

#: preemption-barrier budget: persist_lazy_tail stops materializing
#: after this many seconds (journal-first ordering means whatever was
#: not materialized is still replayable)
PREEMPT_DEADLINE_ENV = "PYABC_TPU_PREEMPT_DEADLINE_S"


def _preempt_deadline_s() -> float:
    try:
        return float(os.environ.get(PREEMPT_DEADLINE_ENV, "30"))
    except ValueError:
        return 30.0


def create_sqlite_db_id(dir_: Optional[str] = None,
                        file_: str = "pyabc_test.db") -> str:
    """Convenience sqlite identifier ``sqlite:///<dir>/<file>`` (reference
    history.py:64-86; defaults to the system temp dir — fine for tests,
    use a durable location for real runs)."""
    import tempfile
    base = dir_ if dir_ is not None else tempfile.gettempdir()
    return "sqlite:///" + os.path.join(base, file_)

_SCHEMA = """
CREATE TABLE IF NOT EXISTS abc_smc (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    start_time TEXT,
    json_parameters TEXT,
    distance TEXT,
    epsilon TEXT,
    population_strategy TEXT
);
CREATE TABLE IF NOT EXISTS populations (
    abc_smc_id INTEGER,
    t INTEGER,
    epsilon REAL,
    nr_samples INTEGER,
    population_end_time TEXT,
    lazy INTEGER DEFAULT 0,
    summary TEXT,
    summary_grid BLOB,
    PRIMARY KEY (abc_smc_id, t)
);
CREATE TABLE IF NOT EXISTS model_populations (
    abc_smc_id INTEGER,
    t INTEGER,
    m INTEGER,
    name TEXT,
    p_model REAL,
    n_particles INTEGER,
    theta BLOB,
    weight BLOB,
    distance BLOB,
    stats BLOB,
    param_names TEXT,
    stat_spec TEXT,
    digest TEXT,
    PRIMARY KEY (abc_smc_id, t, m)
);
CREATE TABLE IF NOT EXISTS observed_data (
    abc_smc_id INTEGER,
    key TEXT,
    value BLOB,
    tag TEXT DEFAULT 'npy',
    PRIMARY KEY (abc_smc_id, key)
);
CREATE TABLE IF NOT EXISTS sub_checkpoints (
    abc_smc_id INTEGER,
    t INTEGER,
    rounds INTEGER,
    n_accepted INTEGER,
    nr_evaluations INTEGER,
    eps REAL,
    m BLOB,
    theta BLOB,
    distance BLOB,
    log_weight BLOB,
    stats BLOB,
    created TEXT,
    manifest TEXT,
    digest TEXT,
    PRIMARY KEY (abc_smc_id, t)
);
"""


def _blob_crc(blob: Optional[bytes]) -> Optional[int]:
    if blob is None:
        return None
    return zlib.crc32(blob) & 0xFFFFFFFF


def _pack(arr: np.ndarray) -> bytes:
    """Array -> blob.  Routed through the wire codec (delta + zlib,
    ``wire/transfer.py``) unless ``$PYABC_TPU_WIRE_CODEC=raw``; falls
    back to plain ``np.save`` for anything the codec refuses."""
    arr = np.asarray(arr)
    from ..wire import transfer as _transfer
    if _transfer.wire_codec() != "raw":
        try:
            return _transfer.encode_array(arr)
        except (ValueError, TypeError):
            pass
    buf = io.BytesIO()
    np.save(buf, arr, allow_pickle=False)
    return buf.getvalue()


def _unpack(blob: bytes) -> np.ndarray:
    """Blob -> array; sniffs the codec magic so databases written with
    either packing (or by older versions) stay readable."""
    if bytes(blob[:4]) == b"PTW1":
        from ..wire import transfer as _transfer
        return _transfer.decode_array(blob)
    return np.load(io.BytesIO(blob), allow_pickle=False)


class History:
    """SQLite-backed run history.

    ``db`` may be a path, ``"sqlite://"`` (in-memory, for benchmarking —
    reference smc.py:272-277) or ``"sqlite:///path"``.

    ``stores_sum_stats`` (reference history.py:120,139,154,681): when
    False, per-particle summary statistics are not persisted — and, going
    one step further than the reference (which still computes and ships
    them to the master before dropping them), the orchestrator then tells
    the sampler to keep the ``[n, s]`` stats block OFF the d2h wire
    entirely when no other host consumer exists (smc.py run()), which at
    the 1e6-particle north star is ~a quarter of the generation's
    transfer budget.  Stats-dependent reads (:meth:`get_sum_stats`,
    weighted-stats queries, resume of an *adaptive-distance* run) then
    return empty, as in the reference.
    """

    def __init__(self, db: str, abc_id: Optional[int] = None,
                 stores_sum_stats: bool = True):
        self.stores_sum_stats = bool(stores_sum_stats)
        if db.startswith("sqlite:///"):
            db = db[len("sqlite:///"):]
        self.in_memory = db in ("sqlite://", ":memory:", "")
        self.db_path = ":memory:" if self.in_memory else db
        # generous busy timeout so concurrent readers (abc-server, a
        # monitoring notebook) and the writer never see transient
        # "database is locked" errors; WAL lets readers proceed while a
        # generation's durable write is in flight
        self._conn = sqlite3.connect(self.db_path, timeout=30.0)
        if not self.in_memory:
            try:
                self._conn.execute("PRAGMA journal_mode=WAL")
            except sqlite3.OperationalError:
                pass  # read-only FS or unsupported: plain journal is fine
        self._conn.executescript(_SCHEMA)
        self._migrate()
        self._conn.commit()
        self.id = abc_id
        #: device-resident population store (wire/store.py) this run's
        #: lazy generations live in; attached by the orchestrator
        self._store = None
        #: write-ahead SpillJournal (resilience/journal.py) — created on
        #: demand (lazy runs / resume recovery), never for plain eager
        #: file DBs
        self._journal = None
        self._journal_armed = False

    @property
    def journal(self):
        """The run's spill journal, created on first use (file-backed
        DBs at ``<db>.journal``; in-memory DBs only under an explicit
        ``$PYABC_TPU_JOURNAL_DIR``); None when journaling is off."""
        if not self._journal_armed:
            self._journal_armed = True
            from ..resilience.journal import journal_for_history
            self._journal = journal_for_history(self)
        return self._journal

    def _existing_journal(self):
        """The journal ONLY if it is already armed or its directory
        already exists on disk — resume recovery must find a previous
        process's journal without creating directories for runs that
        never journaled."""
        if self._journal_armed:
            return self._journal
        from ..resilience.journal import journal_dir_for
        d = journal_dir_for(self.db_path, self.in_memory)
        if d and os.path.isdir(d):
            return self.journal
        return None

    def _unpack_checked(self, blob, crc, *, t=-2, where="db.read"):
        """``_unpack`` behind the stored-blob CRC: every read of a
        digest-bearing row is an integrity check, and a flipped bit in
        the database raises ``IntegrityError`` instead of decoding into
        a silently wrong posterior."""
        if blob is None:
            return None
        if crc is not None:
            from ..resilience.journal import IntegrityError
            from ..telemetry.metrics import REGISTRY
            _help = "checksummed hydration; see resilience/journal.py"
            REGISTRY.counter("store_integrity_checks_total", _help).inc()
            if _blob_crc(blob) != int(crc):
                REGISTRY.counter("store_integrity_failures_total",
                                 _help).inc()
                from ..telemetry.flight import RECORDER
                RECORDER.note("integrity", t=int(t), where=where,
                              detail="stored blob CRC mismatch")
                raise IntegrityError(
                    f"generation {t}: stored blob failed its CRC "
                    f"({where}) — database bytes are corrupt",
                    t=t, where=where)
        return _unpack(blob)

    def _migrate(self):
        """In-place schema upgrades for databases written by older
        versions (CREATE TABLE IF NOT EXISTS does not add new columns).
        The ``DEFAULT 'npy'`` matches the old fixed-format blobs, so
        pre-upgrade rows stay readable."""
        cols = [r[1] for r in self._conn.execute(
            "PRAGMA table_info(observed_data)").fetchall()]
        if "tag" not in cols:
            self._conn.execute(
                "ALTER TABLE observed_data ADD COLUMN tag TEXT "
                "DEFAULT 'npy'")
        pop_cols = [r[1] for r in self._conn.execute(
            "PRAGMA table_info(populations)").fetchall()]
        if "lazy" not in pop_cols:
            self._conn.execute(
                "ALTER TABLE populations ADD COLUMN lazy INTEGER "
                "DEFAULT 0")
        if "summary" not in pop_cols:
            self._conn.execute(
                "ALTER TABLE populations ADD COLUMN summary TEXT")
        if "summary_grid" not in pop_cols:
            self._conn.execute(
                "ALTER TABLE populations ADD COLUMN summary_grid BLOB")
        mp_cols = [r[1] for r in self._conn.execute(
            "PRAGMA table_info(model_populations)").fetchall()]
        if "digest" not in mp_cols:
            self._conn.execute(
                "ALTER TABLE model_populations ADD COLUMN digest TEXT")
        ck_cols = [r[1] for r in self._conn.execute(
            "PRAGMA table_info(sub_checkpoints)").fetchall()]
        if "manifest" not in ck_cols:
            self._conn.execute(
                "ALTER TABLE sub_checkpoints ADD COLUMN manifest TEXT")
        if "digest" not in ck_cols:
            self._conn.execute(
                "ALTER TABLE sub_checkpoints ADD COLUMN digest TEXT")

    # ---- run registration ------------------------------------------------

    def store_initial_data(self, ground_truth_model: Optional[int],
                           options: dict,
                           observed_sum_stat: Dict,
                           ground_truth_parameter: Optional[dict],
                           model_names: List[str],
                           distance_function_json: str = "{}",
                           eps_function_json: str = "{}",
                           population_strategy_json: str = "{}"):
        """Register a new run (reference history.py:374-418)."""
        cur = self._conn.execute(
            "INSERT INTO abc_smc (start_time, json_parameters, distance,"
            " epsilon, population_strategy) VALUES (?,?,?,?,?)",
            (datetime.datetime.now().isoformat(),
             json.dumps({"ground_truth_model": ground_truth_model,
                         "ground_truth_parameter":
                             {k: float(v) for k, v
                              in dict(ground_truth_parameter).items()}
                             if ground_truth_parameter else None,
                         "model_names": model_names, **(options or {})}),
             distance_function_json, eps_function_json,
             population_strategy_json))
        self.id = cur.lastrowid
        for key, val in observed_sum_stat.items():
            # arbitrary types survive storage (reference
            # dataframe_bytes_storage.py:102-104: DataFrames & any object,
            # not just float arrays)
            tag, blob = to_bytes(val)
            self._conn.execute(
                "INSERT OR REPLACE INTO observed_data VALUES (?,?,?,?)",
                (self.id, key, blob, tag))
        self._conn.commit()
        return self.id

    def observed_sum_stat(self) -> Dict:
        rows = self._conn.execute(
            "SELECT key, value, tag FROM observed_data WHERE abc_smc_id=?",
            (self.id,)).fetchall()
        return {k: from_bytes(tag, v) for k, v, tag in rows}

    # ---- append (the per-generation durable write) -----------------------

    def append_population(self, t: int, current_epsilon: float,
                          population: Population, nr_simulations: int,
                          model_names: List[str],
                          param_names: Optional[List[str]] = None,
                          stat_spec: Optional[dict] = None):
        """Bulk array-blob write (replaces reference history.py:617-693).

        ``stat_spec`` maps sum-stat key -> shape; stored alongside the flat
        stats block so reads reconstruct keyed per-particle sum-stats
        (:meth:`get_sum_stats`) without a row-per-statistic table.

        Every statement is INSERT OR REPLACE and the commit is the
        durability point, so the write is idempotent — a transient
        sqlite failure (locked / busy / disk I/O) is simply retried
        through the shared policy (resilience/retry.py).
        """
        from ..resilience import faults as _faults
        from ..resilience import retry as _retry
        _retry.shared_policy().call(
            self._append_population_once, _faults.SITE_APPEND,
            t, current_epsilon, population, nr_simulations, model_names,
            param_names, stat_spec)

    def _append_population_once(self, t, current_epsilon, population,
                                nr_simulations, model_names,
                                param_names=None, stat_spec=None,
                                summary_json=None, summary_grid=None):
        probs = np.asarray(population.get_model_probabilities(
            nr_models=len(model_names)))
        self._conn.execute(
            "INSERT OR REPLACE INTO populations (abc_smc_id, t, epsilon,"
            " nr_samples, population_end_time, lazy, summary,"
            " summary_grid) VALUES (?,?,?,?,?,0,?,?)",
            (self.id, t, float(current_epsilon), int(nr_simulations),
             datetime.datetime.now().isoformat(), summary_json,
             summary_grid))
        m_arr = np.asarray(population.m)
        theta = np.asarray(population.theta)
        w = np.asarray(population.weight)
        d = np.asarray(population.distance)
        stats = (population.sum_stats.get("__flat__")
                 if self.stores_sum_stats else None)
        # np.asarray on a device-resident block is the transfer — when the
        # flag is off it must never run
        stats = np.asarray(stats) if stats is not None else None
        per_model_names = (param_names
                           and isinstance(param_names[0], (list, tuple)))
        for m in range(len(model_names)):
            idx = np.nonzero(m_arr == m)[0]
            if idx.size == 0:
                continue
            names_m = (param_names[m] if per_model_names else param_names)
            blobs = {
                "theta": _pack(theta[idx]), "weight": _pack(w[idx]),
                "distance": _pack(d[idx]),
                "stats": _pack(stats[idx]) if stats is not None else None,
            }
            digest = json.dumps({k: _blob_crc(v)
                                 for k, v in blobs.items()
                                 if v is not None})
            self._conn.execute(
                "INSERT OR REPLACE INTO model_populations (abc_smc_id,"
                " t, m, name, p_model, n_particles, theta, weight,"
                " distance, stats, param_names, stat_spec, digest)"
                " VALUES (?,?,?,?,?,?,?,?,?,?,?,?,?)",
                (self.id, t, m, model_names[m], float(probs[m]),
                 int(idx.size),
                 blobs["theta"], blobs["weight"], blobs["distance"],
                 blobs["stats"],
                 json.dumps(list(names_m or [])),
                 json.dumps({k: list(v) for k, v in stat_spec.items()})
                 if stat_spec else None, digest))
        # the generation is durable in the same transaction, so its
        # mid-generation ledger row (if any) is obsolete
        self._conn.execute(
            "DELETE FROM sub_checkpoints WHERE abc_smc_id=? AND t=?",
            (self.id, t))
        self._conn.commit()

    # ---- mid-generation sub-checkpoints (resilience/checkpoint.py) -------

    def save_sub_checkpoint(self, t: int, batch: Optional[Dict],
                            rounds: int, nr_evaluations: int,
                            eps: Optional[float] = None,
                            manifest: Optional[dict] = None):
        """REPLACE the round-granular accepted-particle ledger for
        generation ``t``: the CUMULATIVE accepted rows through device
        round ``rounds`` (``batch`` is a ``widen_wire``-shaped host
        dict).  One row per generation — a crash between flushes loses
        at most one flush interval, and :meth:`append_population`
        deletes the row once the full generation is durable.

        In lazy-History mode, steady-state flushes pass ``batch=None``
        plus a device-store ``manifest`` — a cadence heartbeat with no
        raw bytes; the raw batch is re-shipped only when a preemption
        is actually in progress (resilience/checkpoint.py)."""
        from ..resilience import faults as _faults
        from ..resilience import retry as _retry

        def _write():
            blobs = {
                "m": _pack(batch["m"]) if batch is not None else None,
                "theta": _pack(batch["theta"])
                if batch is not None else None,
                "distance": _pack(batch["distance"])
                if batch is not None else None,
                "log_weight": _pack(batch["log_weight"])
                if batch is not None else None,
                "stats": _pack(batch["stats"])
                if batch is not None and batch.get("stats") is not None
                else None,
            }
            digest = json.dumps({k: _blob_crc(v)
                                 for k, v in blobs.items()
                                 if v is not None})
            self._conn.execute(
                "INSERT OR REPLACE INTO sub_checkpoints (abc_smc_id, t,"
                " rounds, n_accepted, nr_evaluations, eps, m, theta,"
                " distance, log_weight, stats, created, manifest,"
                " digest) VALUES (?,?,?,?,?,?,?,?,?,?,?,?,?,?)",
                (self.id, int(t), int(rounds),
                 int(batch["m"].shape[0]) if batch is not None else 0,
                 int(nr_evaluations),
                 float(eps) if eps is not None else None,
                 blobs["m"], blobs["theta"], blobs["distance"],
                 blobs["log_weight"], blobs["stats"],
                 datetime.datetime.now().isoformat(),
                 json.dumps(manifest) if manifest is not None else None,
                 digest))
            self._conn.commit()

        _retry.shared_policy().call(_write, _faults.SITE_APPEND)

    def load_sub_checkpoint(self, t: int) -> Optional[Dict]:
        """The flushed ledger for generation ``t``, or None.  Returns
        ``{rounds, nr_evaluations, eps, n_accepted, batch}`` with the
        batch in ``widen_wire`` layout, ready for
        ``Sample.splice_front``.  Manifest-only rows (lazy mode's
        steady-state heartbeat — no raw blobs) return None: there is
        nothing to splice."""
        row = self._conn.execute(
            "SELECT rounds, n_accepted, nr_evaluations, eps, m, theta,"
            " distance, log_weight, stats, digest FROM sub_checkpoints"
            " WHERE abc_smc_id=? AND t=?", (self.id, int(t))).fetchone()
        if row is None or row[4] is None:
            return None
        crcs = json.loads(row[9]) if row[9] else {}
        batch = {
            "m": self._unpack_checked(
                row[4], crcs.get("m"), t=t, where="checkpoint.splice"),
            "theta": self._unpack_checked(
                row[5], crcs.get("theta"), t=t,
                where="checkpoint.splice"),
            "distance": self._unpack_checked(
                row[6], crcs.get("distance"), t=t,
                where="checkpoint.splice"),
            "log_weight": self._unpack_checked(
                row[7], crcs.get("log_weight"), t=t,
                where="checkpoint.splice"),
        }
        if row[8] is not None:
            batch["stats"] = self._unpack_checked(
                row[8], crcs.get("stats"), t=t,
                where="checkpoint.splice")
        return {"rounds": int(row[0]), "n_accepted": int(row[1]),
                "nr_evaluations": int(row[2]),
                "eps": float(row[3]) if row[3] is not None else None,
                "batch": batch}

    def load_sub_checkpoint_manifest(self, t: int) -> Optional[dict]:
        """The device-store manifest recorded with generation ``t``'s
        ledger row (lazy mode), or None."""
        row = self._conn.execute(
            "SELECT manifest FROM sub_checkpoints WHERE abc_smc_id=?"
            " AND t=?", (self.id, int(t))).fetchone()
        if row is None or row[0] is None:
            return None
        return json.loads(row[0])

    def clear_sub_checkpoint(self, t: int):
        self._conn.execute(
            "DELETE FROM sub_checkpoints WHERE abc_smc_id=? AND t=?",
            (self.id, int(t)))
        self._conn.commit()

    # ---- lazy mode: device-resident populations (wire/store.py) ----------
    #
    # In ``history_mode="lazy"`` the orchestrator attaches a
    # DeviceRunStore and appends each generation as a SUMMARY row
    # (``lazy=1`` + O(KB) posterior packet + NULL-blob model rows carrying
    # counts/probabilities) while the full population stays on device.
    # Every blob reader below calls ``_materialize`` first, so hydration
    # is transparent: the first read fetches the wire (booked under
    # ``egress("history")``), replays the exact eager decode, REPLACEs
    # the row with real blobs, and drops the store entry.  Evicted
    # entries arrive through the store's spill queue and are drained
    # HERE — sqlite connections are thread-affine, and this object stays
    # on the orchestrator thread while deposits happen on ingest workers.

    def attach_store(self, store):
        self._store = store
        # arm the durability contract: deposits/evictions write ahead
        # into the journal this History will truncate after commits
        store.attach_journal(self.journal)

    def detach_store(self):
        """Degrade-to-eager rung: the orchestrator abandons lazy mode
        mid-run; subsequent appends take the durable eager path."""
        self._store = None

    def drop_generation(self, t: int):
        """Delete generation ``t``'s rows entirely (the degrade ladder
        re-runs the generation; its summary row must not shadow the
        eager re-append)."""
        self._conn.execute(
            "DELETE FROM populations WHERE abc_smc_id=? AND t=?",
            (self.id, int(t)))
        self._conn.execute(
            "DELETE FROM model_populations WHERE abc_smc_id=? AND t=?",
            (self.id, int(t)))
        self._conn.commit()

    def append_population_lazy(self, t: int, current_epsilon: float,
                               nr_simulations: int, *, summary: dict,
                               model_names: List[str],
                               param_names: Optional[List[str]] = None,
                               stat_spec: Optional[dict] = None,
                               summary_grid: Optional[dict] = None):
        """Durable summary row for a device-resident generation: the
        O(KB) counterpart of :meth:`append_population`.  ``summary`` is
        the posterior summary packet (``wire.store.summary_from_lanes``);
        per-model mass/counts come from its ``model_w``/``model_n``."""
        from ..resilience import faults as _faults
        from ..resilience import retry as _retry
        _retry.shared_policy().call(
            self._append_population_lazy_once, _faults.SITE_APPEND,
            t, current_epsilon, nr_simulations, summary, model_names,
            param_names, stat_spec, summary_grid)

    def _append_population_lazy_once(self, t, current_epsilon,
                                     nr_simulations, summary,
                                     model_names, param_names, stat_spec,
                                     summary_grid):
        self._drain_spills(defer_pod=True)
        grid_blob = None
        if summary_grid:
            grid_blob = _pack(np.stack(
                [np.asarray(summary_grid["grid_centroid"]),
                 np.asarray(summary_grid["grid_log_mass"])]))
        self._conn.execute(
            "INSERT OR REPLACE INTO populations (abc_smc_id, t, epsilon,"
            " nr_samples, population_end_time, lazy, summary,"
            " summary_grid) VALUES (?,?,?,?,?,1,?,?)",
            (self.id, int(t), float(current_epsilon),
             int(nr_simulations), datetime.datetime.now().isoformat(),
             json.dumps(summary), grid_blob))
        model_w = list(summary.get("model_w", []))
        model_n = list(summary.get("model_n", []))
        per_model_names = (param_names
                           and isinstance(param_names[0], (list, tuple)))
        for m in range(len(model_names)):
            n_m = int(model_n[m]) if m < len(model_n) else 0
            if n_m <= 0:
                continue
            names_m = (param_names[m] if per_model_names else param_names)
            p_m = float(model_w[m]) if m < len(model_w) else 0.0
            self._conn.execute(
                "INSERT OR REPLACE INTO model_populations (abc_smc_id,"
                " t, m, name, p_model, n_particles, theta, weight,"
                " distance, stats, param_names, stat_spec) VALUES "
                "(?,?,?,?,?,?,NULL,NULL,NULL,NULL,?,?)",
                (self.id, int(t), m, model_names[m], p_m, n_m,
                 json.dumps(list(names_m or [])),
                 json.dumps({k: list(v) for k, v in stat_spec.items()})
                 if stat_spec else None))
        self._conn.execute(
            "DELETE FROM sub_checkpoints WHERE abc_smc_id=? AND t=?",
            (self.id, int(t)))
        self._conn.commit()

    def _lazy_flag(self, t: int) -> Optional[tuple]:
        """(lazy, epsilon, nr_samples, summary) of generation ``t``'s
        row, or None when absent."""
        return self._conn.execute(
            "SELECT lazy, epsilon, nr_samples, summary FROM populations"
            " WHERE abc_smc_id=? AND t=?", (self.id, int(t))).fetchone()

    def _materialize_pop(self, t: int, pop: Population, eps, nr,
                         summary_json):
        """REPLACE generation ``t``'s summary row with real blobs —
        the exact eager write path, with names/spec recovered from the
        lazy model rows, and the summary packet preserved."""
        names = self.model_names()
        rows = self._conn.execute(
            "SELECT m, param_names, stat_spec FROM model_populations"
            " WHERE abc_smc_id=? AND t=? ORDER BY m",
            (self.id, int(t))).fetchall()
        if not names:
            m_arr = np.asarray(pop.m)
            max_m = int(m_arr.max()) if m_arr.size else 0
            names = [f"m{i}" for i in range(max_m + 1)]
        pn = {m: (json.loads(p) if p else []) for m, p, _ in rows}
        param_names = [pn.get(m, []) for m in range(len(names))]
        spec = None
        for _, _, s in rows:
            if s:
                spec = {k: tuple(v) for k, v in json.loads(s).items()}
                break
        grid_row = self._conn.execute(
            "SELECT summary_grid FROM populations WHERE abc_smc_id=?"
            " AND t=?", (self.id, int(t))).fetchone()
        self._append_population_once(
            int(t), eps, pop, nr, names, param_names, spec,
            summary_json=summary_json,
            summary_grid=grid_row[0] if grid_row else None)
        # the sqlite commit above is the durability point: only now may
        # the journal forget this generation (truncate-behind)
        self._journal_done(int(t))

    def _journal_done(self, t: int):
        journal = self._journal if self._journal_armed else None
        if journal is not None and journal.has_payload(t):
            journal.mark_materialized(t)

    def _hydrate_checked(self, t: int, entry: dict):
        """``hydrate_entry`` behind the recovery ladder.  On
        ``IntegrityError``: (1) a corrupt journaled host copy is dropped
        and the decode retried from the still-resident device wire;
        (2) the journal's own copy of the generation is re-read and
        decoded; then the error propagates for the caller's DB-fallback
        / degrade-to-eager rung."""
        from ..resilience.journal import IntegrityError
        from ..telemetry.metrics import REGISTRY
        from ..wire.store import hydrate_entry
        _help = "hydration recovery ladder; see resilience/journal.py"
        try:
            return hydrate_entry(entry)
        except IntegrityError as first:
            logger.warning("generation %d failed checksummed hydration "
                           "(%s) — walking the recovery ladder", t,
                           first)
            if entry.get("host_wire") is not None \
                    and entry.get("wire") is not None:
                retry_entry = dict(entry)
                retry_entry.pop("host_wire", None)
                if retry_entry.get("digest"):
                    retry_entry["digest"] = dict(
                        retry_entry["digest"], crc=None)
                try:
                    pop = hydrate_entry(retry_entry)
                    REGISTRY.counter(
                        "store_integrity_recovered_total", _help).inc()
                    return pop
                except IntegrityError:
                    pass
            journal = self._journal if self._journal_armed else None
            if journal is not None and journal.has_payload(t):
                try:
                    jentry = journal.pending().get(int(t))
                    if jentry is not None:
                        pop = hydrate_entry(jentry)
                        REGISTRY.counter(
                            "store_integrity_recovered_total",
                            _help).inc()
                        return pop
                except IntegrityError:
                    pass
            raise

    def _drain_spills(self, defer_pod: bool = False):
        """Materialize entries the store's ring evicted (deposits happen
        on ingest worker threads; the durable write happens here, on the
        connection's thread).  Each entry materializes under its own
        retry (``history.materialize`` fault site) — a failure requeues
        THAT entry (``store_spill_requeued_total``) and the drain moves
        on, so one bad entry can no longer drop the rest of the batch
        on the floor.

        ``defer_pod``: the per-generation steady-state call site.  A
        multi-process materialization is a cross-host allgather, and
        the steady state must stay free of host-side collectives (the
        shard bytes are already journaled by the eviction), so pod runs
        requeue everything here and materialize only at the explicit
        SPMD-ordered drain points (flush, reader hydration, recovery)."""
        store = self._store
        if store is None:
            return
        from ..resilience import faults as _faults
        from ..resilience import retry as _retry
        from ..resilience.journal import IntegrityError
        if defer_pod:
            import jax
            if jax.process_count() > 1:
                return
        requeue = []
        for entry in store.take_spills():
            t = entry["t"]
            row = self._lazy_flag(t)
            if row is None:
                # the one-ahead fetch worker can evict generation t+1
                # into the spill queue BEFORE the harvest loop has
                # appended its summary row — not stale, just early:
                # keep it queued for the next drain
                requeue.append(entry)
                continue
            if not row[0]:
                self._journal_done(t)
                continue  # stale spill: the row is already durable
            try:
                _retry.shared_policy().call(
                    self._materialize_spill_once,
                    _faults.SITE_MATERIALIZE, entry, row)
            except (_retry.RetryExhausted, IntegrityError) as err:
                logger.warning(
                    "spill drain: generation %d not materialized (%s) "
                    "— requeued for the next drain", t, err)
                from ..telemetry.flight import RECORDER
                RECORDER.note("spill_requeue", t=int(t),
                              detail=type(err).__name__)
                requeue.append(entry)
        if requeue:
            store.requeue_spills(requeue)

    def _materialize_spill_once(self, entry: dict, row: tuple):
        from ..telemetry.metrics import REGISTRY
        t = entry["t"]
        pop = self._hydrate_checked(t, entry)
        if pop is None:
            return
        self._materialize_pop(t, pop, row[1], row[2], row[3])
        REGISTRY.counter("wire_store_spills_total",
                         "evicted store entries made durable").inc()

    def _materialize(self, t: int) -> bool:
        """Ensure generation ``t``'s row has real blobs.  True when the
        row exists and is durable after the call; False when it stayed
        summary-only (store evicted AND spill already lost, or no store
        attached — readers then take their empty-result paths)."""
        row = self._lazy_flag(t)
        if row is None or not row[0]:
            return row is not None
        self._drain_spills()
        row = self._lazy_flag(t)
        if row is None or not row[0]:
            return row is not None
        store = self._store
        if store is None or not store.has(int(t)):
            return False
        pop = self._store_hydrate(store, int(t))
        if pop is None:
            return False
        self._materialize_pop(int(t), pop, row[1], row[2], row[3])
        store.drop(int(t))
        return True

    def _store_hydrate(self, store, t: int):
        """``store.hydrate`` with the IntegrityError recovery ladder
        behind it; an unrecoverable mismatch propagates so the
        orchestrator can take its degrade-to-eager rung."""
        from ..resilience.journal import IntegrityError
        try:
            return store.hydrate(t)
        except IntegrityError:
            entry = store.entry(t)
            if entry is None:
                raise
            return self._hydrate_checked(t, entry)

    def hydrate_population(self, t: int) -> Population:
        """Round-order Population of generation ``t`` for in-run
        consumers (transition fits, eps updates): decoded straight from
        the store wire — bit-identical to what the eager mode handed
        them — with the durable write done as a side effect.  Falls back
        to the DB blobs (model-grouped order, as any resumed run sees)
        when the generation is no longer device-resident."""
        self._drain_spills()
        store = self._store
        row = self._lazy_flag(t)
        if (store is not None and store.has(int(t)) and row is not None
                and row[0]):
            pop = self._store_hydrate(store, int(t))
            if pop is not None:
                self._materialize_pop(int(t), pop, row[1], row[2],
                                      row[3])
                store.drop(int(t))
                return pop
        self._materialize(t)
        return self.get_population(t)

    def flush_lazy(self, final_only: Optional[bool] = None,
                   newest_first: bool = False,
                   deadline: Optional[float] = None):
        """Materialize device-resident lazy generations (run end).  By
        default ALL of them — the finished DB then has full blobs for
        every generation, same as eager mode, just shipped off the
        per-generation critical path.  ``$PYABC_TPU_LAZY_FINAL_ONLY=1``
        keeps only the final generation's blobs (pure summary steady
        state; intermediate generations stay summary rows).

        ``deadline`` (absolute ``time.monotonic``) bounds the flush:
        past it, remaining generations stay resident/journaled instead
        of being dropped — a preemption barrier must never discard what
        it ran out of time to materialize."""
        if final_only is None:
            final_only = os.environ.get(
                "PYABC_TPU_LAZY_FINAL_ONLY", "0").lower() in (
                "1", "true", "on")
        self._drain_spills()
        store = self._store
        if store is None:
            return
        ts = store.resident_ts()
        if final_only and ts:
            for t in ts[:-1]:
                store.drop(t)
            ts = ts[-1:]
        if newest_first:
            ts = list(reversed(ts))
        timed_out = False
        for t in ts:
            if deadline is not None and time.monotonic() >= deadline:
                timed_out = True
                logger.warning(
                    "lazy flush: deadline hit with %d generation(s) "
                    "left un-materialized — their journal/device "
                    "copies survive for recovery",
                    len(ts) - ts.index(t))
                break
            self._materialize(t)
        if not timed_out:
            for t in store.resident_ts():
                store.drop(t)
            journal = self._journal if self._journal_armed else None
            if journal is not None:
                journal.compact()

    def persist_lazy_tail(self, deadline_s: Optional[float] = None):
        """Exit-path durability anchor, in two bounded phases:

        1. **journal-first** — append the packed bytes of every
           un-journaled resident generation, NEWEST first
           (``DeviceRunStore.journal_tail``): cheap fsync'd appends, so
           even a second kill seconds later leaves a fully replayable
           journal;
        2. best-effort **materialize**, newest-first, so the resume
           anchor (max durable t) is as late as possible.

        The whole barrier is bounded by ``deadline_s`` (default
        ``$PYABC_TPU_PREEMPT_DEADLINE_S`` = 30 s) — platform kill
        timeouts are real, and an over-budget flush would otherwise
        turn a clean preemption into a hard kill mid-commit."""
        if deadline_s is None:
            deadline_s = _preempt_deadline_s()
        deadline = (time.monotonic() + float(deadline_s)
                    if deadline_s and deadline_s > 0 else None)
        store = self._store
        if store is not None:
            # make sure the journal is armed even if attach_store ran
            # before journaling was possible
            if store.journal is None and self.journal is not None:
                store.attach_journal(self.journal)
            store.journal_tail(deadline)
        self.flush_lazy(newest_first=True, deadline=deadline)

    def recover_lazy(self) -> dict:
        """Startup recovery (``ABCSMC.load``): replay the previous
        process's un-materialized journal payloads into durable blobs —
        generations a crash stranded device-side are RESTORED, not
        discarded — then purge whatever is still summary-only (deposits
        whose bytes never reached the journal).  Returns
        ``{"recovered": n, "purged": m}``."""
        from ..telemetry.metrics import REGISTRY
        from ..resilience.journal import pod_pending
        recovered = 0
        journal = self._existing_journal()
        if journal is not None:
            # pod runs journal per-host shards into sibling h<NNN>
            # directories; pod_pending reassembles full generations
            for t, entry in sorted(pod_pending(journal).items()):
                row = self._lazy_flag(t)
                if row is None or not row[0]:
                    # no lazy row to fill: either the summary row never
                    # committed (nothing to anchor a recovery to) or
                    # the generation is already durable — either way
                    # the journal can forget it
                    journal.mark_materialized(t)
                    continue
                try:
                    pop = self._hydrate_checked(t, entry)
                except Exception:
                    logger.exception(
                        "journal replay: generation %d undecodable — "
                        "left for purge", t)
                    continue
                if pop is None:
                    continue
                self._materialize_pop(t, pop, row[1], row[2], row[3])
                recovered += 1
                REGISTRY.counter(
                    "resilience_journal_replayed_total",
                    "journal payloads replayed into durable blobs"
                ).inc()
            journal.compact()
        if recovered:
            logger.warning(
                "recovered %d generation(s) from the spill journal "
                "left by an interrupted lazy run", recovered)
        purged = self.purge_stale_lazy()
        return {"recovered": recovered, "purged": purged}

    def purge_stale_lazy(self) -> int:
        """Drop summary-only generation rows whose device store died
        with a previous process (resume path): ``max_t`` then anchors on
        the last generation with durable blobs, and the run regenerates
        from there.  Returns the number of generations purged."""
        ts = [r[0] for r in self._conn.execute(
            "SELECT t FROM populations WHERE abc_smc_id=? AND lazy=1",
            (self.id,)).fetchall()]
        live = set(self._store.resident_ts()) if self._store else set()
        stale = [t for t in ts if t not in live]
        for t in stale:
            self._conn.execute(
                "DELETE FROM populations WHERE abc_smc_id=? AND t=?",
                (self.id, t))
            self._conn.execute(
                "DELETE FROM model_populations WHERE abc_smc_id=?"
                " AND t=?", (self.id, t))
        if stale:
            self._conn.commit()
            import logging
            logging.getLogger("ABC.History").warning(
                "purged %d summary-only generation(s) %s left by an "
                "interrupted lazy run; resuming from the last durable "
                "generation", len(stale), stale)
        return len(stale)

    def get_population_summary(self, t: Optional[int] = None
                               ) -> Optional[dict]:
        """The stored posterior summary packet of generation ``t``
        (lazy appends always have one; materialization preserves it),
        or None for eager-written generations."""
        t = self.max_t if t is None else t
        row = self._conn.execute(
            "SELECT summary FROM populations WHERE abc_smc_id=? AND t=?",
            (self.id, int(t))).fetchone()
        if row is None or row[0] is None:
            return None
        return json.loads(row[0])

    # ---- queries (reference history.py:269-330, 732-780, 1004-1078) ------

    @property
    def max_t(self) -> int:
        row = self._conn.execute(
            "SELECT MAX(t) FROM populations WHERE abc_smc_id=? AND t>=0",
            (self.id,)).fetchone()
        return row[0] if row and row[0] is not None else -1

    @property
    def n_populations(self) -> int:
        return self.max_t + 1

    def alive_models(self, t: Optional[int] = None) -> List[int]:
        t = self.max_t if t is None else t
        rows = self._conn.execute(
            "SELECT m FROM model_populations WHERE abc_smc_id=? AND t=? "
            "AND p_model>0 ORDER BY m", (self.id, t)).fetchall()
        return [r[0] for r in rows]

    def get_model_probabilities(self, t: Optional[int] = None) -> pd.DataFrame:
        if t is None:
            rows = self._conn.execute(
                "SELECT t, m, p_model FROM model_populations WHERE "
                "abc_smc_id=? AND t>=0 ORDER BY t, m", (self.id,)).fetchall()
            df = pd.DataFrame(rows, columns=["t", "m", "p"])
            return df.pivot(index="t", columns="m", values="p").fillna(0.0)
        rows = self._conn.execute(
            "SELECT m, p_model FROM model_populations WHERE abc_smc_id=? "
            "AND t=? ORDER BY m", (self.id, t)).fetchall()
        probs = pd.Series({m: p for m, p in rows})
        return probs

    def get_distribution(self, m: int = 0, t: Optional[int] = None
                         ) -> Tuple[pd.DataFrame, np.ndarray]:
        """(parameter DataFrame, normalized weights) — reference
        history.py:269-330."""
        t = self.max_t if t is None else t
        self._materialize(t)
        row = self._conn.execute(
            "SELECT theta, weight, param_names FROM model_populations "
            "WHERE abc_smc_id=? AND t=? AND m=?", (self.id, t, m)).fetchone()
        if row is None or row[0] is None:
            return pd.DataFrame(), np.zeros(0)
        theta, w = _unpack(row[0]), _unpack(row[1])
        names = json.loads(row[2]) or [f"p{i}" for i in range(theta.shape[1])]
        df = pd.DataFrame(theta[:, :len(names)], columns=names)
        return df, w / w.sum()

    def get_all_populations(self) -> pd.DataFrame:
        rows = self._conn.execute(
            "SELECT t, epsilon, nr_samples, population_end_time FROM "
            "populations WHERE abc_smc_id=? ORDER BY t", (self.id,)).fetchall()
        return pd.DataFrame(
            rows, columns=["t", "epsilon", "samples", "population_end_time"])

    def get_nr_particles_per_population(self) -> pd.Series:
        rows = self._conn.execute(
            "SELECT t, SUM(n_particles) FROM model_populations WHERE "
            "abc_smc_id=? GROUP BY t ORDER BY t", (self.id,)).fetchall()
        return pd.Series({t: n for t, n in rows})

    def get_weighted_distances(self, t: Optional[int] = None) -> pd.DataFrame:
        t = self.max_t if t is None else t
        self._materialize(t)
        rows = self._conn.execute(
            "SELECT distance, weight FROM model_populations WHERE "
            "abc_smc_id=? AND t=?", (self.id, t)).fetchall()
        rows = [r for r in rows if r[0] is not None]
        ds = np.concatenate([_unpack(r[0]) for r in rows]) if rows else np.zeros(0)
        ws = np.concatenate([_unpack(r[1]) for r in rows]) if rows else np.zeros(0)
        return pd.DataFrame({"distance": ds, "w": ws / max(ws.sum(), 1e-300)})

    def get_population(self, t: Optional[int] = None) -> Population:
        """Reconstruct the dense Population (reference history.py:1004-1078)."""
        t = self.max_t if t is None else t
        self._materialize(t)
        rows = self._conn.execute(
            "SELECT m, theta, weight, distance, stats FROM model_populations "
            "WHERE abc_smc_id=? AND t=? ORDER BY m", (self.id, t)).fetchall()
        rows = [r for r in rows if r[1] is not None]
        if not rows:
            return Population(
                m=np.zeros(0, dtype=np.int32), theta=np.zeros((0, 0)),
                weight=np.zeros(0), distance=np.zeros(0), sum_stats={})
        ms, thetas, ws, ds, stats = [], [], [], [], []
        dim = max((_unpack(r[1]).shape[1] for r in rows), default=0)
        for m, tb, wb, db, sb in rows:
            th = _unpack(tb)
            n = th.shape[0]
            if th.shape[1] < dim:
                th = np.pad(th, ((0, 0), (0, dim - th.shape[1])))
            ms.append(np.full(n, m, dtype=np.int32))
            thetas.append(th)
            ws.append(_unpack(wb))
            ds.append(_unpack(db))
            if sb is not None:
                stats.append(_unpack(sb))
        # numpy arrays: resumed populations feed host-side fits/quantiles
        sum_stats = ({"__flat__": np.concatenate(stats)}
                     if stats and len(stats) == len(rows) else {})
        return Population(
            m=np.concatenate(ms),
            theta=np.concatenate(thetas),
            weight=np.concatenate(ws),
            distance=np.concatenate(ds),
            sum_stats=sum_stats)

    def get_sum_stats(self, t: Optional[int] = None, m: int = 0
                      ) -> Dict[str, np.ndarray]:
        """Keyed per-particle sum-stats ``{key: [N, *shape]}`` for model
        ``m`` (reference history.py:732-780 ``get_sum_stats``; the flat
        block + stored spec replace the row-per-statistic ORM)."""
        t = self.max_t if t is None else t
        self._materialize(t)
        row = self._conn.execute(
            "SELECT stats, stat_spec FROM model_populations "
            "WHERE abc_smc_id=? AND t=? AND m=?", (self.id, t, m)).fetchone()
        if row is None or row[0] is None:
            return {}
        flat = _unpack(row[0])
        if not row[1]:
            return {"__flat__": flat}
        spec = json.loads(row[1])
        out, off = {}, 0
        for k in sorted(spec):
            shape = tuple(spec[k])
            size = int(np.prod(shape, dtype=int))
            out[k] = flat[:, off:off + size].reshape((flat.shape[0],) + shape)
            off += size
        return out

    def _raw_weighted_sum_stats(self, t: int, m: int
                                ) -> Tuple[np.ndarray, List[Dict]]:
        """Un-normalized (weights, per-particle sum-stat dicts) of one
        model — shared by the all-models and per-model accessors."""
        self._materialize(t)
        row = self._conn.execute(
            "SELECT weight FROM model_populations WHERE abc_smc_id=? "
            "AND t=? AND m=?", (self.id, t, m)).fetchone()
        if row is None or row[0] is None:
            return np.zeros(0), []
        w = _unpack(row[0])
        keyed = self.get_sum_stats(t, m)
        dicts = [{k: v[i] for k, v in keyed.items()}
                 for i in range(w.shape[0])]
        return w, dicts

    def get_weighted_sum_stats(self, t: Optional[int] = None
                               ) -> Tuple[np.ndarray, List[Dict]]:
        """(weights, one sum-stat dict per particle) across all models —
        reference history.py:1004-1040 signature."""
        t = self.max_t if t is None else t
        rows = self._conn.execute(
            "SELECT m FROM model_populations WHERE abc_smc_id=? "
            "AND t=? ORDER BY m", (self.id, t)).fetchall()
        weights, dicts = [], []
        for (m,) in rows:
            w, d = self._raw_weighted_sum_stats(t, m)
            weights.append(w)
            dicts.extend(d)
        if not weights:
            return np.zeros(0), []
        w = np.concatenate(weights)
        return w / max(w.sum(), 1e-300), dicts

    def get_population_strategy(self) -> dict:
        row = self._conn.execute(
            "SELECT population_strategy FROM abc_smc WHERE id=?",
            (self.id,)).fetchone()
        return json.loads(row[0]) if row and row[0] else {}

    def all_runs(self) -> pd.DataFrame:
        rows = self._conn.execute(
            "SELECT id, start_time FROM abc_smc").fetchall()
        return pd.DataFrame(rows, columns=["id", "start_time"])

    # ---- reference-surface accessors (history.py:88-132, 418-470) --------

    def db_file(self) -> str:
        return self.db_path

    @property
    def db_size(self) -> float:
        """DB size in MB, -1 for in-memory (reference history.py:125-132)."""
        if self.in_memory:
            return -1.0
        try:
            return os.path.getsize(self.db_path) / 1e6
        except OSError:
            return -1.0

    @property
    def total_nr_simulations(self) -> int:
        row = self._conn.execute(
            "SELECT SUM(nr_samples) FROM populations WHERE abc_smc_id=?",
            (self.id,)).fetchone()
        return int(row[0] or 0)

    def _json_parameters(self) -> dict:
        row = self._conn.execute(
            "SELECT json_parameters FROM abc_smc WHERE id=?",
            (self.id,)).fetchone()
        return json.loads(row[0]) if row and row[0] else {}

    def get_ground_truth_parameter(self) -> dict:
        """(reference history.py:418-434)."""
        return self._json_parameters().get("ground_truth_parameter") or {}

    def nr_of_models_alive(self, t: Optional[int] = None) -> int:
        return len(self.alive_models(t))

    def get_weighted_sum_stats_for_model(self, m: int = 0,
                                         t: Optional[int] = None
                                         ) -> Tuple[np.ndarray, List[Dict]]:
        """(weights, sum-stat dicts) for one model (reference
        history.py:966-1002)."""
        t = self.max_t if t is None else t
        w, dicts = self._raw_weighted_sum_stats(t, m)
        if w.size == 0:
            return w, dicts
        return w / max(w.sum(), 1e-300), dicts

    def get_population_extended(self, m: Optional[int] = None,
                                t: Union[int, str, None] = "last"
                                ) -> pd.DataFrame:
        """Long-form particle table over generations (reference
        history.py:1043-1078): columns t, m, w, distance + parameters."""
        if t == "last":
            ts = [self.max_t]
        elif t is None or t == "all":
            # includes the calibration sample (t = PRE_TIME), as the
            # reference's unfiltered query does
            ts = [r[0] for r in self._conn.execute(
                "SELECT DISTINCT t FROM model_populations WHERE "
                "abc_smc_id=? ORDER BY t", (self.id,)).fetchall()]
        else:
            ts = [int(t)]
        frames = []
        for ti in ts:
            query = ("SELECT m, theta, weight, distance, param_names FROM "
                     "model_populations WHERE abc_smc_id=? AND t=?")
            args = [self.id, ti]
            if m is not None:
                query += " AND m=?"
                args.append(m)
            self._materialize(ti)
            rows = self._conn.execute(query + " ORDER BY m",
                                      args).fetchall()
            rows = [r for r in rows if r[1] is not None]
            for mi, tb, wb, db_, names_json in rows:
                theta = _unpack(tb)
                names = (json.loads(names_json)
                         or [f"p{i}" for i in range(theta.shape[1])])
                df = pd.DataFrame(theta[:, :len(names)], columns=names)
                df.insert(0, "distance", _unpack(db_))
                df.insert(0, "w", _unpack(wb))
                df.insert(0, "m", mi)
                df.insert(0, "t", ti)
                frames.append(df)
        if not frames:
            return pd.DataFrame(columns=["t", "m", "w", "distance"])
        return pd.concat(frames, ignore_index=True)

    def model_names(self) -> List[str]:
        return self._json_parameters().get("model_names", [])

    @classmethod
    def from_reference_db(cls, path: str, db: str = "sqlite://",
                          abc_id: int = 1) -> "History":
        """Load a run written by the REFERENCE pyABC package (ORM schema)
        into a native History backed by ``db`` — existing pyABC databases
        resume/plot/export with this framework (see
        storage/reference_export.py)."""
        from .reference_export import from_reference_db
        return from_reference_db(path, db=db, abc_id=abc_id)

    def to_reference_db(self, path: str, batch_stats: bool = True) -> int:
        """Export this run into the reference pyABC ORM schema at ``path``
        so the reference's own tooling can read it (see
        storage/reference_export.py; schema:
        /root/reference/pyabc/storage/db_model.py:35-127)."""
        from .reference_export import to_reference_db
        return to_reference_db(self, path, batch_stats=batch_stats)

    def done(self):
        self.flush_lazy()
        self._conn.commit()

    def close(self):
        if self._journal_armed and self._journal is not None:
            self._journal.close()
        self._conn.close()
