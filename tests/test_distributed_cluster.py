"""Real multi-process cluster bring-up through the worker CLI.

Parity: reference ``RedisEvalParallelSamplerServerStarter``
(redis_eps/redis_sampler_server_starter.py:10-76) spawns a real broker +
worker processes for tests.  The TPU-native analog spawns worker
subprocesses through the ACTUAL ``abc-distributed-worker`` CLI: each joins
a real ``jax.distributed`` coordinator, heartbeats into the shared run
dir, runs its script, and exits cleanly.

Scope: the control plane (coordinator handshake, process identity,
heartbeats, clean shutdown) AND the cross-host data plane — under
``jax.distributed`` the CPU backend federates each process's device into
one global mesh, so ``test_multihost_abcsmc`` runs a REAL 2-process
ABCSMC whose ShardedSampler rounds are cross-host SPMD with allgather
materialization (sampler/base.py fetch_to_host).
"""

import json
import os
import socket
import subprocess
import sys
import time

from pyabc_tpu.parallel import health

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


WORKER_SCRIPT = """
import json, os, time
import jax
out = os.environ["CLUSTER_TEST_OUT"]
with open(out, "w") as f:
    json.dump({"process_index": jax.process_index(),
               "process_count": jax.process_count()}, f)
time.sleep(3)  # stay up long enough for the manager-side liveness check
"""


def test_worker_cli_forms_real_cluster(tmp_path):
    n = 2
    port = _free_port()
    run_dir = str(tmp_path / "run")
    script = tmp_path / "prog.py"
    script.write_text(WORKER_SCRIPT)

    procs = []
    for i in range(n):
        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            PYTHONPATH=REPO,
            CLUSTER_TEST_OUT=str(tmp_path / f"out_{i}.json"),
        )
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "pyabc_tpu.parallel.cli",
             "--coordinator", f"127.0.0.1:{port}",
             "--num-processes", str(n), "--process-id", str(i),
             "--run-dir", run_dir, str(script)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE))

    # while both run, heartbeats must appear (poll up to the full timeout)
    deadline = time.monotonic() + 90
    seen_two = False
    while time.monotonic() < deadline:
        if all(p.poll() is not None for p in procs):
            break
        if len(health.worker_status(run_dir)) >= n:
            seen_two = True
        time.sleep(0.2)

    outs = [p.communicate(timeout=30) for p in procs]
    for p, (so, se) in zip(procs, outs):
        assert p.returncode == 0, se.decode()[-2000:]

    # every worker saw the SAME cluster through a real coordinator
    for i in range(n):
        with open(tmp_path / f"out_{i}.json") as f:
            info = json.load(f)
        assert info == {"process_index": i, "process_count": n}
    assert seen_two, "heartbeats never showed both workers alive"
    # clean exits deregistered the heartbeats
    assert health.worker_status(run_dir) == []


def test_worker_cli_crash_leaves_stale_heartbeat(tmp_path):
    """A worker that dies mid-script stays visible as STALE (the
    worker-death-detection contract, multicorebase.py:78-105)."""
    port = _free_port()
    run_dir = str(tmp_path / "run")
    script = tmp_path / "bad.py"
    script.write_text("raise RuntimeError('worker crashed')\n")

    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    p = subprocess.Popen(
        [sys.executable, "-m", "pyabc_tpu.parallel.cli",
         "--coordinator", f"127.0.0.1:{port}",
         "--num-processes", "1", "--process-id", "0",
         "--run-dir", run_dir, str(script)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE)
    _, se = p.communicate(timeout=90)
    assert p.returncode != 0
    status = health.worker_status(run_dir, stale_after_s=1e9)
    assert len(status) == 1, se.decode()[-2000:]


ABC_PROGRAM = """
import json, os
import jax
import pyabc_tpu as pt
from pyabc_tpu.models import make_two_gaussians_problem

models, priors, distance, observed, _ = make_two_gaussians_problem()
# SAME seed on every host: SPMD requires identical replicated inputs
abc = pt.ABCSMC(models, priors, distance, population_size=128, seed=17)
abc.new("sqlite://", observed)
h = abc.run(max_nr_populations=2)
probs = h.get_model_probabilities(h.max_t)

# the stochastic triple across the same cluster: exercises the record
# machinery + temperature schemes (incl. the device-records fast path
# or its graceful host fallback) under multi-process SPMD
def m1(key, theta):
    return {"y": theta[:, 0]
            + 0.1 * jax.random.normal(key, (theta.shape[0],))}

abc2 = pt.ABCSMC(m1, pt.Distribution(a=pt.RV("norm", 0, 1)),
                 pt.IndependentNormalKernel(var=0.01),
                 population_size=96, eps=pt.Temperature(),
                 acceptor=pt.StochasticAcceptor(), seed=23)
abc2.new("sqlite://", {"y": 0.5})
h2 = abc2.run(max_nr_populations=2)
df2, w2 = h2.get_distribution()
post_mean = float(df2["a"].to_numpy() @ w2)
temp_last = float(h2.get_all_populations().epsilon.iloc[-1])

out = os.environ["CLUSTER_TEST_OUT"]
with open(out, "w") as f:
    json.dump({"process_index": jax.process_index(),
               "n_devices": len(jax.devices()),
               "sampler": type(abc.sampler).__name__,
               "max_t": int(h.max_t),
               "p1": float(probs.get(1, 0.0)),
               "stoch_max_t": int(h2.max_t),
               "stoch_post_mean": post_mean,
               "stoch_temp": temp_last}, f)
"""


def test_multihost_abcsmc(tmp_path):
    """A full ABCSMC inference across a REAL 2-process cluster: the
    default sampler shards rounds over the federated 2-device mesh and
    every host materializes the same global population."""
    n = 2
    port = _free_port()
    script = tmp_path / "abc_prog.py"
    script.write_text(ABC_PROGRAM)

    procs = []
    for i in range(n):
        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            PYTHONPATH=REPO,
            # 4 virtual devices per process -> an 8-device global mesh
            # where each process addresses only half: multi-device AND
            # multi-process sharding at once
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            CLUSTER_TEST_OUT=str(tmp_path / f"abc_out_{i}.json"),
        )
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "pyabc_tpu.parallel.cli",
             "--coordinator", f"127.0.0.1:{port}",
             "--num-processes", str(n), "--process-id", str(i),
             str(script)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE))

    outs = [p.communicate(timeout=300) for p in procs]
    for p, (so, se) in zip(procs, outs):
        assert p.returncode == 0, se.decode()[-3000:]

    infos = []
    for i in range(n):
        with open(tmp_path / f"abc_out_{i}.json") as f:
            infos.append(json.load(f))
    for i, info in enumerate(infos):
        assert info["process_index"] == i
        assert info["n_devices"] == 8          # federated global mesh
        assert info["sampler"] == "ShardedSampler"
        assert info["max_t"] == 1
    # SPMD: every host computed the SAME global model probabilities
    assert abs(infos[0]["p1"] - infos[1]["p1"]) < 1e-12
    assert 0.3 < infos[0]["p1"] <= 1.0
    # stochastic triple: bit-identical cross-host temperature schedule
    # and posterior through the record/temperature machinery
    assert infos[0]["stoch_max_t"] >= 1
    assert abs(infos[0]["stoch_post_mean"]
               - infos[1]["stoch_post_mean"]) < 1e-12
    assert abs(infos[0]["stoch_temp"] - infos[1]["stoch_temp"]) < 1e-9
    assert abs(infos[0]["stoch_post_mean"] - 0.5) < 0.4
