"""Fast weighted index sampling — the reference's ``fast_random_choice``,
TPU-shaped.

Parity: pyabc/pyabc_rand_choice.py:4-17 speeds up small weighted draws by
replacing ``np.random.choice``'s machinery with a linear CDF scan.  The
TPU analog solves the opposite regime: ``jax.random.categorical(key, logits,
shape=(n,))`` materializes an ``[n, N]`` Gumbel block — 2.6e11 elements at
the 1e6-population scale.  The inverse-CDF formulation here went through
two designs: cumsum + ``jnp.searchsorted`` (35x over categorical, 6.2 s ->
0.18 s at n=2^19, N=5e5) and then a two-level blocked count (see
:func:`fast_weighted_choice`) after the binary search's ~log2(N) serial
random-gather steps per lane proved to dominate the whole sampling round
(a further ~17x on the inversion at n=2^19, N=2^20).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

Array = jnp.ndarray


#: support-block width for the two-level inverse-CDF search; the refine
#: step gathers one contiguous [n, _BLOCK] slab (TPU-friendly row gather)
_BLOCK = 256


def _cap_draws(cdf: Array, u: Array) -> Array:
    """Cap draws strictly below ``cdf[-1]``.

    A draw scaled by cdf[-1] can round UP to exactly cdf[-1] in f32, in
    which case no cdf[i] > u exists and the inversion counts hit N — and
    a plain N-1 clamp would land on a zero-weight padded row.  Capping u
    at the float just below cdf[-1] routes the draw to the LAST
    positive-weight index instead (trailing flat CDF segments all equal
    cdf[-1], so the first cdf[i] > u is the final real entry).  The same
    strictly-below-cap property makes flat (zero-weight) segments
    unhittable even when u lands EXACTLY on their value.
    """
    return jnp.minimum(u, jnp.nextafter(cdf[-1], jnp.zeros((), cdf.dtype)))


def _invert_cdf(cdf: Array, u: Array) -> Array:
    """``idx = smallest i with cdf[i] > u`` for every draw, as a
    TWO-LEVEL vectorized search, not ``jnp.searchsorted``: binary search
    lowers to ~log2(N) serial random-gather steps per lane, which
    dominated the whole sampling round at the 1e6 scale (measured
    ~0.08 s/round at n=2^19, N=2^20 — >90 % of the non-KDE round cost).
    Instead the block-end CDF values are compared against every draw in
    one fused broadcast-reduce (no gathers), then ONE contiguous
    [n, block] row gather + count refines within the block — all
    parallel VPU work.  ``u`` must already be capped (:func:`_cap_draws`).
    """
    N = cdf.shape[0]
    if N <= _BLOCK * 4:
        # small support: one fused compare-reduce over the whole CDF
        idx = jnp.sum((cdf[None, :] <= u[:, None]).astype(jnp.int32),
                      axis=1)
        return jnp.minimum(idx, N - 1).astype(jnp.int32)
    n_blocks = -(-N // _BLOCK)
    pad = n_blocks * _BLOCK - N
    # pad with cdf[-1] (edge): strictly above every capped u, so padding
    # is never counted by either level
    cdf_p = jnp.pad(cdf, (0, pad), mode="edge") if pad else cdf
    blocks = cdf_p.reshape(n_blocks, _BLOCK)
    coarse = blocks[:, -1]                                    # [C]
    # level 1: first block whose end exceeds u (fused, gather-free)
    blk = jnp.sum((coarse[None, :] <= u[:, None]).astype(jnp.int32),
                  axis=1)
    blk = jnp.minimum(blk, n_blocks - 1)
    # level 2: contiguous row gather + count within the block
    rows = blocks[blk]                                        # [n, BLOCK]
    off = jnp.sum((rows <= u[:, None]).astype(jnp.int32), axis=1)
    idx = blk * _BLOCK + off
    return jnp.minimum(idx, N - 1).astype(jnp.int32)


def fast_weighted_choice(key, log_w: Array, n: int) -> Array:
    """``n`` indices sampled ∝ ``exp(log_w)`` (unnormalized log weights).

    Padded entries with log_w ≈ -inf get zero probability mass (flat CDF
    segments are never hit by a strictly-below-cap uniform draw).  The
    inversion is the shared two-level search (:func:`_invert_cdf`).
    """
    w = jax.nn.softmax(log_w)
    cdf = jnp.cumsum(w)
    u = jax.random.uniform(key, (n,), dtype=cdf.dtype) * cdf[-1]
    return _invert_cdf(cdf, _cap_draws(cdf, u))


def systematic_weighted_choice(key, log_w: Array, n: int) -> Array:
    """Systematic (stratified) resampling: ``n`` indices ∝ ``exp(log_w)``
    from ONE uniform draw, ``u_i = (u0 + i)/n · cdf[-1]``.

    The classic low-variance resampler: every index with weight
    ≥ 1/n mass appears ⌊n·w⌋ or ⌈n·w⌉ times, so the resampled support
    preserves the weighted moments to O(1/n) instead of the O(1/√n)
    of i.i.d. draws — exactly what the fused capped-support refit wants
    (the KDE covariance is a weighted second moment).  Sorted draws also
    make the two-level inversion's block gathers near-sequential.
    Consumes one scalar uniform, not ``n``.
    """
    w = jax.nn.softmax(log_w)
    cdf = jnp.cumsum(w)
    u0 = jax.random.uniform(key, (), dtype=cdf.dtype)
    u = (u0 + jnp.arange(n, dtype=cdf.dtype)) / n * cdf[-1]
    return _invert_cdf(cdf, _cap_draws(cdf, u))


def residual_weighted_choice(log_w: Array, n: int,
                             rank_cap: int = None) -> Array:
    """Deterministic residual resampling: ``n`` indices ∝ ``exp(log_w)``
    with zero sampling noise — ⌊n·w⌋ copies each, the remaining slots to
    the largest remainders.

    The residual *ranking* is the interesting part at scale: below
    ``rank_cap`` support points it is an exact ``argsort(-residual)``;
    above, it routes through the sort-free top-k sketch
    (``ops.quantile_sketch.sketch_topk_mask``) — same counts except for
    residuals within the sketch resolution (~1e-6) of the cut, and the
    sub-cap program stays byte-identical because the cap check is a
    static shape test (``weighted_statistics.
    resample_indices_deterministic``, which owns the cap default).
    """
    from ..weighted_statistics import (RESIDUAL_RANK_CAP,
                                       resample_indices_deterministic)
    w = jax.nn.softmax(log_w)
    if rank_cap is None:
        rank_cap = RESIDUAL_RANK_CAP
    return resample_indices_deterministic(w, n, rank_cap=rank_cap)
