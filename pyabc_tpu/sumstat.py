"""Summary-statistic handling: named pytrees <-> dense ``[N, S]`` blocks.

The reference passes summary statistics as per-particle dicts of scalars or
arrays (pyabc/model.py:114-116, distance/distance.py:92-103 iterates dict
keys in Python).  On TPU, distances are computed for the whole population at
once, so sum-stats live as a dict of batched arrays ``{key: Array[N, ...]}``
and are flattened once into a dense ``f32[N, S]`` block for the distance
kernels.  ``SumStatSpec`` fixes the (sorted) key order and per-key sizes so
per-key weights broadcast to per-component weight vectors.
"""

from __future__ import annotations

from typing import Dict, Mapping, Tuple

import jax.numpy as jnp
import numpy as np

Array = jnp.ndarray


class SumStatSpec:
    """Fixed ordering/shapes of summary-statistic keys.

    Keys are sorted alphabetically (the reference iterates sorted weight
    keys, distance/distance.py:255-258, so ordering is deterministic there
    too).
    """

    def __init__(self, shapes: Mapping[str, Tuple[int, ...]]):
        self.keys: tuple = tuple(sorted(shapes.keys()))
        self.shapes: Dict[str, Tuple[int, ...]] = {
            k: tuple(shapes[k]) for k in self.keys
        }
        self.sizes: Dict[str, int] = {
            k: int(np.prod(self.shapes[k], dtype=int)) for k in self.keys
        }
        offsets = np.cumsum([0] + [self.sizes[k] for k in self.keys])
        self.offsets: Dict[str, int] = {
            k: int(offsets[i]) for i, k in enumerate(self.keys)
        }
        self.total_size: int = int(offsets[-1])

    @classmethod
    def from_example(cls, x: Mapping[str, Array], batched: bool = False) -> "SumStatSpec":
        """Infer the spec from one observed dict (or a batched dict)."""
        shapes = {}
        for k, v in x.items():
            v = jnp.asarray(v)
            shapes[k] = tuple(v.shape[1:]) if batched else tuple(jnp.shape(v))
        return cls(shapes)

    # ---- flattening ------------------------------------------------------

    def flatten(self, x: Mapping[str, Array]) -> Array:
        """``{key: [N, ...]} -> f32[N, S]`` (jit-safe)."""
        parts = []
        for k in self.keys:
            v = jnp.asarray(x[k], dtype=jnp.float32)
            n = v.shape[0]
            parts.append(v.reshape(n, -1))
        return jnp.concatenate(parts, axis=-1)

    def flatten_single(self, x0: Mapping[str, Array]) -> Array:
        """``{key: [...]} -> f32[S]`` for the observed data (jit-safe)."""
        parts = [
            jnp.asarray(x0[k], dtype=jnp.float32).reshape(-1) for k in self.keys
        ]
        return jnp.concatenate(parts, axis=-1)

    def unflatten(self, flat: Array) -> Dict[str, Array]:
        """``f32[..., S] -> {key: [..., *shape]}``."""
        out = {}
        for k in self.keys:
            o, s = self.offsets[k], self.sizes[k]
            out[k] = flat[..., o:o + s].reshape(flat.shape[:-1] + self.shapes[k])
        return out

    def expand_key_values(self, per_key: Mapping[str, float],
                          default: float = 1.0) -> np.ndarray:
        """Per-key scalars -> per-component ``f32[S]`` vector.

        This is how the reference's per-key weight dicts
        (distance/distance.py:60-78) map onto the dense block.
        """
        vec = np.full(self.total_size, default, dtype=np.float32)
        for k, val in per_key.items():
            if k not in self.offsets:
                raise KeyError(f"unknown sum-stat key {k!r}; have {self.keys}")
            o, s = self.offsets[k], self.sizes[k]
            vec[o:o + s] = np.asarray(val, dtype=np.float32).reshape(-1)
        return vec

    def collapse_to_keys(self, vec: np.ndarray) -> Dict[str, np.ndarray]:
        """Per-component vector -> per-key arrays (for logging/provenance)."""
        vec = np.asarray(vec)
        return {
            k: vec[self.offsets[k]:self.offsets[k] + self.sizes[k]].reshape(
                self.shapes[k] if self.shapes[k] else ()
            )
            for k in self.keys
        }

    def __repr__(self):
        return f"SumStatSpec({self.shapes})"
