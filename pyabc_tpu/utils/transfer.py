"""Compatibility shim: the canonical transfer ledger lives in the wire
subsystem (``pyabc_tpu/wire/transfer.py``) since streaming ingest landed
— the counters are per-stage now (``compute_s``/``fetch_s``/
``overlap_s`` next to the historical ``d2h_*``/``h2d_*`` keys).  This
module re-exports it unchanged so existing imports keep working."""

from ..wire.transfer import (  # noqa: F401
    _lock,
    _state,
    _tree_nbytes,
    delta,
    record_compute,
    record_d2h,
    record_h2d,
    record_overlap,
    snapshot,
    timed_d2h,
)
