"""ABC-as-a-service: multi-tenant study serving on warm workers.

The serving tier turns "a fast run" (one :class:`~pyabc_tpu.ABCSMC`
driving one study) into "a service": many small studies from many
tenants multiplexed onto a persistent worker that keeps its compiled
programs warm across studies.  Five pieces:

- :mod:`pyabc_tpu.serve.spec` — the study spec (prior + model +
  distance + eps config + observed data) and its canonical
  content-address digest;
- :mod:`pyabc_tpu.serve.queue` — the admission queue over the
  ``parallel/`` mount contract, with per-tenant quotas, backpressure
  and priority aging;
- :mod:`pyabc_tpu.serve.cache` — the content-addressed study cache
  (digest → posterior summary) serving duplicate submissions without a
  dispatch;
- :mod:`pyabc_tpu.serve.multiplex` — the study axis: N small studies
  vmapped into ONE fused program with per-study live-sentinel masking;
- :mod:`pyabc_tpu.serve.worker` — the persistent warm worker
  (``abc-serve``) pinning the AOT :class:`CompiledLadder` across
  studies and routing eligible ones through ``run_mode="onedispatch"``.

All serving knobs are serve-prefixed environment variables,
documented in ``docs/serving.md``.
"""

from .cache import StudyCache
from .multiplex import StudyBatch, lane_eligible, multiplex_eligible
from .queue import (QueueFull, SpecAuthError, StudyQueue,
                    TenantQuotaExceeded)
from .spec import StudySpec, problem_key, study_digest
from .worker import ServeWorker

__all__ = [
    "QueueFull",
    "ServeWorker",
    "SpecAuthError",
    "StudyBatch",
    "StudyCache",
    "StudyQueue",
    "StudySpec",
    "TenantQuotaExceeded",
    "lane_eligible",
    "multiplex_eligible",
    "problem_key",
    "study_digest",
]
