"""Distances over summary statistics (parity: pyabc/distance/)."""

from .base import (
    AcceptAllDistance,
    Distance,
    IdentityFakeDistance,
    NoDistance,
    SimpleFunctionDistance,
    to_distance,
)
from .distance import (
    DistanceWithMeasureList,
    AdaptiveAggregatedDistance,
    AdaptivePNormDistance,
    AggregatedDistance,
    MinMaxDistance,
    PCADistance,
    PercentileDistance,
    PNormDistance,
    RangeEstimatorDistance,
    ZScoreDistance,
)
from .kernel import (
    SCALE_LIN,
    SCALE_LOG,
    BinomialKernel,
    IndependentLaplaceKernel,
    IndependentNormalKernel,
    NegativeBinomialKernel,
    NormalKernel,
    PoissonKernel,
    SimpleFunctionKernel,
    StochasticKernel,
)
from . import scale
from .scale import (
    combined_mean_absolute_deviation,
    combined_median_absolute_deviation,
    mean_absolute_deviation,
    median_absolute_deviation,
    root_mean_square_deviation,
    standard_deviation,
)

__all__ = [
    "DistanceWithMeasureList",
    "Distance", "NoDistance", "AcceptAllDistance", "IdentityFakeDistance",
    "SimpleFunctionDistance", "to_distance",
    "PNormDistance", "AdaptivePNormDistance", "AggregatedDistance",
    "AdaptiveAggregatedDistance", "ZScoreDistance", "PCADistance",
    "RangeEstimatorDistance", "MinMaxDistance", "PercentileDistance",
    "StochasticKernel", "SimpleFunctionKernel", "NormalKernel",
    "IndependentNormalKernel", "IndependentLaplaceKernel", "BinomialKernel",
    "PoissonKernel", "NegativeBinomialKernel", "SCALE_LIN", "SCALE_LOG",
    "scale", "standard_deviation", "median_absolute_deviation",
    "mean_absolute_deviation", "root_mean_square_deviation",
    "combined_mean_absolute_deviation", "combined_median_absolute_deviation",
]
