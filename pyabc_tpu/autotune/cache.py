"""Persistent XLA compilation-cache wiring (opt-in).

The batch ladder's programs are pure functions of (kernel config, rung,
population target) — exactly the workload JAX's persistent compilation
cache was built for: once a rung has been compiled anywhere, a later
process pays a cache *read* instead of an XLA compile.  This module is
the single place the cache directory is resolved:

- ``ABCSMC(compile_cache="/path")`` wins;
- else the ``PYABC_TPU_COMPILE_CACHE`` environment variable;
- else the cache stays off (JAX default) and this module is a no-op.

``min_compile_time_secs`` defaults to 0 so even the small CPU-backend
test kernels persist — the upstream default (1 s) silently skips
everything the tier-1 suite compiles, which would make the warm-run
assertion vacuous.

Import direction: like telemetry, autotune is a LEAF package — nothing
here imports from the rest of ``pyabc_tpu``.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

logger = logging.getLogger("ABC.Autotune")

#: environment variable naming the persistent compile-cache directory
COMPILE_CACHE_ENV = "PYABC_TPU_COMPILE_CACHE"


def configure_compile_cache(path: Optional[str] = None,
                            min_compile_time_secs: float = 0.0,
                            ) -> Optional[str]:
    """Point JAX's persistent compilation cache at ``path`` (explicit
    argument, else ``$PYABC_TPU_COMPILE_CACHE``); returns the resolved
    directory, or ``None`` when neither names one (no-op)."""
    resolved = path if path is not None \
        else os.environ.get(COMPILE_CACHE_ENV)
    if not resolved:
        return None
    resolved = os.path.abspath(os.path.expanduser(str(resolved)))
    os.makedirs(resolved, exist_ok=True)
    import jax

    previous = getattr(jax.config, "jax_compilation_cache_dir", None)
    jax.config.update("jax_compilation_cache_dir", resolved)
    try:
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(min_compile_time_secs))
    except Exception:  # config knob renamed across jax versions
        pass
    if previous != resolved:
        # jax latches cache state at the FIRST compile of the process
        # (compilation_cache._cache_used/_cache): anything compiled
        # before this call — e.g. construction-time capability probes —
        # would leave the cache off (or pointed at a stale dir) for the
        # whole process.  reset_cache() drops the latch so the next
        # compile re-initializes against the directory just configured.
        try:
            from jax._src.compilation_cache import reset_cache
            reset_cache()
        except Exception:  # private API drifted: stale latch, not fatal
            logger.debug("compilation_cache.reset_cache unavailable",
                         exc_info=True)
    logger.info("persistent compile cache: %s", resolved)
    return resolved
