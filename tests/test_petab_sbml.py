"""PEtab problem-directory ingestion: SBML subset parser, expression
compiler, and the zero-code importer (parity: reference
AmiciPetabImporter, pyabc/petab/amici.py:26-170 — a petab problem in,
runnable model/prior/kernel out, no user model code)."""

import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import pyabc_tpu as pt
from pyabc_tpu.petab import (PetabProblem, SBMLPetabImporter, parse_sbml)
from pyabc_tpu.petab.sbml import (ExprError, eval_expr, expr_names,
                                  mathml_to_infix)

SBML_DECAY = textwrap.dedent("""\
    <?xml version="1.0" encoding="UTF-8"?>
    <sbml xmlns="http://www.sbml.org/sbml/level3/version2/core"
          level="3" version="2">
      <model id="decay">
        <listOfCompartments>
          <compartment id="cell" size="1" constant="true"/>
        </listOfCompartments>
        <listOfSpecies>
          <species id="A" compartment="cell" initialConcentration="1"
                   boundaryCondition="false" constant="false"/>
        </listOfSpecies>
        <listOfParameters>
          <parameter id="k1" value="0.7" constant="true"/>
        </listOfParameters>
        <listOfReactions>
          <reaction id="degrade" reversible="false">
            <listOfReactants>
              <speciesReference species="A" stoichiometry="1"/>
            </listOfReactants>
            <kineticLaw>
              <math xmlns="http://www.w3.org/1998/Math/MathML">
                <apply><times/><ci>k1</ci><ci>A</ci></apply>
              </math>
            </kineticLaw>
          </reaction>
        </listOfReactions>
      </model>
    </sbml>
""")

SBML_RATE_RULE = textwrap.dedent("""\
    <?xml version="1.0" encoding="UTF-8"?>
    <sbml xmlns="http://www.sbml.org/sbml/level3/version2/core"
          level="3" version="2">
      <model id="raterule">
        <listOfCompartments>
          <compartment id="c" size="1" constant="true"/>
        </listOfCompartments>
        <listOfSpecies>
          <species id="x" compartment="c" initialConcentration="2"
                   constant="false"/>
        </listOfSpecies>
        <listOfParameters>
          <parameter id="k" value="0.5" constant="true"/>
          <parameter id="x_scaled" value="0" constant="false"/>
        </listOfParameters>
        <listOfRules>
          <rateRule variable="x">
            <math xmlns="http://www.w3.org/1998/Math/MathML">
              <apply><minus/>
                <apply><times/><ci>k</ci><ci>x</ci></apply>
              </apply>
            </math>
          </rateRule>
          <assignmentRule variable="x_scaled">
            <math xmlns="http://www.w3.org/1998/Math/MathML">
              <apply><times/><cn>2.0</cn><ci>x</ci></apply>
            </math>
          </assignmentRule>
        </listOfRules>
      </model>
    </sbml>
""")


# ---------------------------------------------------------------------------
# expression compiler
# ---------------------------------------------------------------------------

def test_eval_expr_arrays():
    env = {"a": jnp.asarray([1.0, 2.0]), "b": 3.0}
    out = eval_expr("a * b + exp(0) - a^2", env)
    np.testing.assert_allclose(np.asarray(out), [3.0, 3.0])


def test_expr_names():
    assert expr_names("k1 * A + exp(offset)") == {"k1", "A", "offset"}


@pytest.mark.parametrize("bad", [
    "__import__('os').system('true')",
    "a.b", "[1,2]", "lambda: 1", "f'{x}'", "open('x')",
])
def test_eval_expr_rejects_non_math(bad):
    with pytest.raises(ExprError):
        eval_expr(bad, {})


def test_eval_expr_unknown_symbol():
    with pytest.raises(ExprError, match="unknown symbol"):
        eval_expr("k_missing * 2", {})


def test_mathml_e_notation_and_log():
    import xml.etree.ElementTree as ET
    m = ET.fromstring(
        '<math xmlns="http://www.w3.org/1998/Math/MathML">'
        '<apply><times/><cn type="e-notation">1.5<sep/>-2</cn>'
        '<apply><ln/><ci>x</ci></apply></apply></math>')
    s = mathml_to_infix(m)
    assert abs(eval_expr(s, {"x": float(np.e)}) - 0.015) < 1e-12


# ---------------------------------------------------------------------------
# SBML parser + RHS
# ---------------------------------------------------------------------------

def test_parse_decay_model():
    doc = parse_sbml(SBML_DECAY)
    assert list(doc.species) == ["A"]
    assert doc.parameters["k1"] == 0.7
    assert doc.state_ids() == ["A"]
    assert doc.y0() == [1.0]
    rhs = doc.make_rhs()
    y = jnp.asarray([[2.0], [4.0]])
    dy = rhs(y, {"k1": jnp.asarray([0.5, 1.0])})
    np.testing.assert_allclose(np.asarray(dy), [[-1.0], [-4.0]])


def test_rate_rule_and_assignment():
    doc = parse_sbml(SBML_RATE_RULE)
    assert doc.state_ids() == ["x"]
    assert "x_scaled" in doc.assignment_rules
    rhs = doc.make_rhs()
    dy = rhs(jnp.asarray([[2.0]]), {})
    np.testing.assert_allclose(np.asarray(dy), [[-1.0]])
    env = doc.resolve_assignments({**doc.base_env(), "x": 3.0})
    assert env["x_scaled"] == 6.0


def test_unsupported_constructs_raise():
    bad = SBML_DECAY.replace(
        "<listOfReactions>",
        "<listOfEvents/><listOfReactions>")
    with pytest.raises(ExprError, match="events"):
        parse_sbml(bad)


# ---------------------------------------------------------------------------
# problem directory -> runnable model, ZERO hand-written model code
# ---------------------------------------------------------------------------

def _write_problem_dir(tmp_path, scale="lin"):
    times = np.asarray([0.5, 1.0, 1.5, 2.0])
    rng = np.random.default_rng(0)
    data = np.exp(-0.7 * times) + 0.05 * rng.normal(size=times.shape)

    (tmp_path / "model.xml").write_text(SBML_DECAY)
    lo, hi = (0.01, 3.0)
    if scale == "log10":
        plo, phi = np.log10(lo), np.log10(hi)
    else:
        plo, phi = lo, hi
    (tmp_path / "parameters.tsv").write_text(
        "parameterId\tparameterScale\tlowerBound\tupperBound\testimate\t"
        "objectivePriorType\tobjectivePriorParameters\n"
        f"k1\t{scale}\t{lo}\t{hi}\t1\t"
        + ("parameterScaleUniform" if scale == "log10" else "uniform")
        + f"\t{plo};{phi}\n")
    (tmp_path / "observables.tsv").write_text(
        "observableId\tobservableFormula\tnoiseFormula\n"
        "obs_a\tA\t0.05\n")
    lines = ["observableId\tsimulationConditionId\ttime\tmeasurement"]
    for t, m in zip(times, data):
        lines.append(f"obs_a\tc0\t{t}\t{m}")
    (tmp_path / "measurements.tsv").write_text("\n".join(lines) + "\n")
    (tmp_path / "conditions.tsv").write_text("conditionId\nc0\n")
    (tmp_path / "problem.yaml").write_text(textwrap.dedent("""\
        format_version: 1
        parameter_file: parameters.tsv
        problems:
          - sbml_files: [model.xml]
            condition_files: [conditions.tsv]
            observable_files: [observables.tsv]
            measurement_files: [measurements.tsv]
    """))
    return tmp_path / "problem.yaml", data, times


def test_from_yaml_llh(tmp_path):
    yaml_path, data, times = _write_problem_dir(tmp_path)
    importer = SBMLPetabImporter.from_yaml(str(yaml_path), n_steps=100)
    prior = importer.create_prior()
    assert prior.space.names == ("k1",)
    model = importer.create_model()
    theta = jnp.asarray([[0.7], [2.5]])
    out = model.simulate(jax.random.PRNGKey(0), theta)
    llh = np.asarray(out["llh"])
    assert llh.shape == (2,)
    # true-parameter llh beats a far-off parameter and matches the
    # analytic solution's llh to integrator tolerance
    analytic = np.exp(-0.7 * times)
    ref_llh = float(np.sum(
        -0.5 * ((data - analytic) / 0.05) ** 2
        - 0.5 * np.log(2 * np.pi * 0.05**2)))
    assert llh[0] > llh[1]
    assert abs(llh[0] - ref_llh) < 0.05


def test_log10_parameter_scale(tmp_path):
    yaml_path, data, times = _write_problem_dir(tmp_path, scale="log10")
    importer = SBMLPetabImporter.from_yaml(str(yaml_path), n_steps=100)
    model = importer.create_model()
    # theta on log10 scale: 10**(-0.1549) ~= 0.7
    theta = jnp.asarray([[np.log10(0.7)]])
    out = model.simulate(jax.random.PRNGKey(0), theta)
    analytic = np.exp(-0.7 * times)
    ref_llh = float(np.sum(
        -0.5 * ((data - analytic) / 0.05) ** 2
        - 0.5 * np.log(2 * np.pi * 0.05**2)))
    assert abs(float(out["llh"][0]) - ref_llh) < 0.05


def test_condition_override_initial(tmp_path):
    yaml_path, _, _ = _write_problem_dir(tmp_path)
    problem = PetabProblem.from_yaml(str(yaml_path))
    import pandas as pd
    problem.condition_df = pd.DataFrame(
        {"conditionId": ["c0"], "A": [2.0]}).set_index("conditionId")
    from pyabc_tpu.petab import PetabSBMLModel
    model = PetabSBMLModel(problem, n_steps=100)
    out = model.simulate(jax.random.PRNGKey(0), jnp.asarray([[0.7]]))
    # doubling the initial concentration shifts the simulated series, so
    # the llh must move away from the (un-overridden) fit
    base_model = PetabSBMLModel(PetabProblem.from_yaml(str(yaml_path)),
                                n_steps=100)
    base = base_model.simulate(jax.random.PRNGKey(0), jnp.asarray([[0.7]]))
    assert float(out["llh"][0]) < float(base["llh"][0])


def test_e2e_abc_posterior(tmp_path):
    """Zero-code end-to-end: PEtab dir -> ABCSMC -> posterior covers the
    true rate (the VERDICT round-3 'done' criterion)."""
    yaml_path, _, _ = _write_problem_dir(tmp_path)
    importer = SBMLPetabImporter.from_yaml(str(yaml_path), n_steps=60)
    abc = pt.ABCSMC(
        models=importer.create_model(),
        parameter_priors=importer.create_prior(),
        distance_function=importer.create_kernel(),
        population_size=300,
        eps=pt.Temperature(),
        acceptor=pt.StochasticAcceptor(),
        sampler=pt.VectorizedSampler(),
        seed=1)
    abc.new("sqlite://", importer.get_observed())
    h = abc.run(max_nr_populations=4)
    pop = h.get_population(h.max_t)
    theta = np.asarray(pop.theta)[:, 0]
    w = np.asarray(pop.weight)
    mean = float(np.sum(theta * w))
    assert 0.4 < mean < 1.1, mean


def test_mathml_logbase_and_root_degree():
    import xml.etree.ElementTree as ET
    m = ET.fromstring(
        '<math xmlns="http://www.w3.org/1998/Math/MathML">'
        '<apply><log/><logbase><cn>2</cn></logbase><ci>x</ci></apply>'
        '</math>')
    assert abs(eval_expr(mathml_to_infix(m), {"x": 8.0}) - 3.0) < 1e-6
    m = ET.fromstring(
        '<math xmlns="http://www.w3.org/1998/Math/MathML">'
        '<apply><root/><degree><cn>3</cn></degree><ci>x</ci></apply>'
        '</math>')
    assert abs(eval_expr(mathml_to_infix(m), {"x": 27.0}) - 3.0) < 1e-5


def test_local_kinetic_parameter_collision_raises():
    bad = SBML_DECAY.replace(
        "<kineticLaw>",
        "<kineticLaw><listOfLocalParameters>"
        '<localParameter id="k1" value="0.1"/>'
        "</listOfLocalParameters>")
    with pytest.raises(ExprError, match="collides"):
        parse_sbml(bad)


def test_estimated_parameter_in_observable_formula(tmp_path):
    """The PEtab scaling-observable pattern: observableFormula references
    an estimated parameter alongside a state series."""
    yaml_path, data, times = _write_problem_dir(tmp_path)
    (tmp_path / "parameters.tsv").write_text(
        "parameterId\tparameterScale\tlowerBound\tupperBound\testimate\t"
        "objectivePriorType\tobjectivePriorParameters\n"
        "k1\tlin\t0.01\t3.0\t1\tuniform\t0.01;3.0\n"
        "scale_a\tlin\t0.1\t10.0\t1\tuniform\t0.1;10.0\n")
    (tmp_path / "observables.tsv").write_text(
        "observableId\tobservableFormula\tnoiseFormula\n"
        "obs_a\tscale_a * A\t0.05\n")
    importer = SBMLPetabImporter.from_yaml(str(yaml_path), n_steps=100)
    model = importer.create_model()
    out = model.simulate(jax.random.PRNGKey(0),
                         jnp.asarray([[0.7, 1.0], [0.7, 2.0]]))
    llh = np.asarray(out["llh"])
    assert np.isfinite(llh).all()
    # scale 1.0 matches how the data was generated; scale 2.0 must not
    assert llh[0] > llh[1]


def test_fixed_parameter_nominal_is_linear_scale(tmp_path):
    """nominalValue is linear-scale even when parameterScale is log10:
    a fixed log10 parameter must NOT be exponentiated."""
    yaml_path, data, times = _write_problem_dir(tmp_path)
    (tmp_path / "parameters.tsv").write_text(
        "parameterId\tparameterScale\tlowerBound\tupperBound\testimate\t"
        "nominalValue\n"
        "k1\tlog10\t0.01\t3.0\t0\t0.7\n")
    importer = SBMLPetabImporter.from_yaml(str(yaml_path), n_steps=100)
    model = importer.create_model()
    out = model.simulate(jax.random.PRNGKey(0), jnp.zeros((1, 0)))
    analytic = np.exp(-0.7 * times)
    ref_llh = float(np.sum(
        -0.5 * ((data - analytic) / 0.05) ** 2
        - 0.5 * np.log(2 * np.pi * 0.05**2)))
    assert abs(float(out["llh"][0]) - ref_llh) < 0.05
