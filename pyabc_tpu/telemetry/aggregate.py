"""Cross-host telemetry aggregation over the shared run directory.

Fleet observability rides the SAME mount contract as the heartbeats in
``parallel/health.py``: every host that sees ``PYABC_TPU_RUN_DIR`` (or
is handed an explicit run directory) publishes its telemetry into
``<run_dir>/telemetry/`` —

- ``spans_<host>_<pid>.jsonl`` — the host's Chrome-trace span stream
  (the span tracer is armed with this sink when fleet publishing is on
  and no explicit trace path was configured);
- ``snap_<host>_<pid>.json`` — an atomically-replaced snapshot of the
  metrics registry, wire ledger, egress breakdown, heartbeat summary
  and generation-timeline tail, stamped with a schema version and the
  host's clock anchor.

The aggregation half reads those files back from ANY process (the
``abc-top`` CLI, the ``abc-server`` dashboard, tests):

- :func:`merge_traces` / :func:`write_merged_trace` — one fleet
  Chrome-trace with one track (pid) per host, every host's ``ts``
  shifted onto a common unix timebase via the published
  ``trace_t0_unix`` anchors, so cross-host causality reads directly in
  Perfetto.
- :func:`fleet_rollup` — sum/max/p50/p99 of every numeric metric
  across hosts.
- :func:`render_prometheus` — the rollup as Prometheus text
  (``pyabc_tpu_fleet_*`` samples), the fleet analog of the per-worker
  exporter in ``telemetry/metrics.py``.

Clock model: a span's ``ts`` is microseconds since its tracer's
``perf_counter`` origin.  Each snapshot carries
``clock.trace_t0_unix = time.time() - (perf_counter() - t0)`` — the
wall-clock instant of ``ts == 0``.  The merger picks the earliest
anchor as fleet zero and shifts every host by
``(host_anchor - fleet_zero) * 1e6``, so tracks align to within the
hosts' wall-clock agreement (NTP), which is exactly the guarantee a
shared-filesystem fleet already depends on for heartbeat staleness.

Import direction: telemetry stays a LEAF package — the wire ledger,
heartbeat summary and health helpers are imported function-locally.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from typing import Dict, List, Optional

from . import spans
from .lanes import PROGRESS, merge_progress
from .metrics import REGISTRY, heartbeat_summary

#: bump when the snapshot payload shape changes; consumers check this
#: instead of sniffing formats (heartbeats embed the same version)
SCHEMA_VERSION = 1

#: override the host identity (defaults to ``socket.gethostname()``) —
#: lets one machine fake a fleet (tests) and disambiguates containers
#: that all report the same kernel hostname
HOST_ENV = "PYABC_TPU_HOST_ID"

#: subdirectory of the run directory holding telemetry files
TELEMETRY_SUBDIR = "telemetry"

_SNAP_PREFIX = "snap_"
_SPANS_PREFIX = "spans_"

#: full timeline rows kept in each snapshot (the compact eps/acceptance
#: trajectory is unbounded — a row is ~40 bytes there)
_TIMELINE_TAIL = 64


def host_id() -> str:
    """This process's fleet identity: ``$PYABC_TPU_HOST_ID`` else the
    hostname."""
    return os.environ.get(HOST_ENV) or socket.gethostname()


def telemetry_dir(run_dir: str) -> str:
    return os.path.join(run_dir, TELEMETRY_SUBDIR)


class TelemetryPublisher:
    """Per-process half: throttled snapshot writes + span-sink arming.

    Created by the orchestrator when a run directory is advertised
    (:func:`publisher_from_env`).  ``publish()`` is called at generation
    boundaries on every run path; it is throttled to at most one write
    per ``min_interval_s`` unless forced (run end), so pod-scale fleets
    do not grind the shared filesystem at sub-second generation rates.

    ``publish()`` is thread-safe: during a one-dispatch run the
    :class:`~.lanes.ProgressPoller` thread force-publishes concurrently
    with the main thread's generation-boundary calls, and both target
    the same snapshot path — the write lock keeps the tmp-then-replace
    dance atomic per caller.
    """

    #: lock-discipline contract, enforced by `abc-lint`
    _GUARDED_BY = {"_last_write": "_write_lock"}

    def __init__(self, run_dir: str, min_interval_s: float = 1.0,
                 process_index: Optional[int] = None):
        self._write_lock = threading.Lock()
        self.run_dir = run_dir
        self.min_interval_s = float(min_interval_s)
        self.process_index = process_index
        self.host = host_id()
        self.pid = os.getpid()
        d = telemetry_dir(run_dir)
        os.makedirs(d, exist_ok=True)
        stem = f"{self.host}_{self.pid}"
        self.snap_path = os.path.join(d, f"{_SNAP_PREFIX}{stem}.json")
        self.spans_path = os.path.join(d, f"{_SPANS_PREFIX}{stem}.jsonl")
        self._last_write = 0.0
        # Arm the tracer into the run directory UNLESS the user already
        # pointed it somewhere explicit (ABCSMC(trace_path=...) /
        # $PYABC_TPU_TRACE wins — fleet publishing must not steal a
        # requested local trace).
        if spans.TRACER._path is None:
            spans.TRACER.configure(trace_path=self.spans_path)

    def publish(self, timeline=None, force: bool = False) -> bool:
        """Write one snapshot (+ flush buffered spans).  Returns whether
        a write happened (throttled calls return False).  Never raises:
        a shared-filesystem hiccup must not kill the run it observes."""
        now = time.time()
        with self._write_lock:
            if not force and now - self._last_write < self.min_interval_s:
                return False
            try:
                payload = self._payload(timeline, now)
                tmp = self.snap_path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(payload, f)
                os.replace(tmp, self.snap_path)  # atomic on POSIX
                spans.TRACER.flush()
            except Exception:
                return False
            self._last_write = now
            return True

    def _payload(self, timeline, now: float) -> dict:
        from ..wire import transfer  # function-local: wire imports telemetry

        pod = None
        try:
            import jax
            if jax.process_count() > 1:
                pod = {"process_index": jax.process_index(),
                       "process_count": jax.process_count(),
                       "local_devices": len(jax.local_devices())}
        except Exception:
            pod = None
        payload = {
            "schema_version": SCHEMA_VERSION,
            "host": self.host,
            "pid": self.pid,
            "process_index": self.process_index,
            "pod": pod,
            "written_unix": now,
            "clock": {
                "trace_t0_unix": spans.TRACER.t0_unix(),
                # wall minus monotonic: lets any consumer translate this
                # host's monotonic stamps without loading the trace
                "monotonic_offset_s": time.time() - time.monotonic(),
            },
            "metrics": REGISTRY.to_dict(),
            "wire": transfer.snapshot(),
            "egress": transfer.egress_breakdown(),
            "heartbeat": heartbeat_summary(),
            # the in-dispatch progress word (telemetry/lanes.py): lets
            # readers show generations advancing while this host is
            # still inside a one-dispatch call; None outside such runs
            "run_progress": PROGRESS.read(),
        }
        if timeline is not None:
            rows = timeline.to_rows()
            payload["trajectory"] = [
                {"gen": r["gen"], "eps": r["eps"],
                 "accepted": r["accepted"], "total": r["total"],
                 "wall_s": r["wall_s"], "engine": r["engine"]}
                for r in rows]
            payload["timeline_tail"] = rows[-_TIMELINE_TAIL:]
        return payload


def publisher_from_env(process_index: Optional[int] = None
                       ) -> Optional[TelemetryPublisher]:
    """A publisher for the advertised run directory, or None when no
    run directory is set (the common single-process case: one ``is
    None`` check per generation is the whole disabled-path cost)."""
    from ..parallel import health  # function-local: parallel imports telemetry

    d = health.run_dir()
    if not d:
        return None
    try:
        return TelemetryPublisher(d)
    except OSError:
        return None


# -- aggregation (reader side) ----------------------------------------

def read_snapshots(run_dir: str) -> List[Dict]:
    """Every host snapshot under the run directory, sorted by host/pid.
    Unreadable or schema-incompatible files are skipped, not fatal —
    a crashed host must not take the fleet view down with it."""
    d = telemetry_dir(run_dir)
    out = []
    try:
        names = os.listdir(d)
    except OSError:
        return out
    for name in sorted(names):
        if not (name.startswith(_SNAP_PREFIX) and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(d, name)) as f:
                snap = json.load(f)
        except (OSError, ValueError):
            continue
        if snap.get("schema_version") != SCHEMA_VERSION:
            continue
        out.append(snap)
    out.sort(key=lambda s: (str(s.get("host")), s.get("pid") or 0))
    return out


def _span_files(run_dir: str) -> List[str]:
    d = telemetry_dir(run_dir)
    try:
        names = os.listdir(d)
    except OSError:
        return []
    return sorted(os.path.join(d, n) for n in names
                  if n.startswith(_SPANS_PREFIX) and n.endswith(".jsonl"))


def _stem_of(path: str) -> str:
    name = os.path.basename(path)
    for prefix, suffix in ((_SPANS_PREFIX, ".jsonl"),
                           (_SNAP_PREFIX, ".json")):
        if name.startswith(prefix) and name.endswith(suffix):
            return name[len(prefix):-len(suffix)]
    return name


def merge_traces(run_dir: str) -> List[Dict]:
    """One clock-aligned fleet trace over every host's span file.

    Each host becomes one Chrome-trace process track: its events are
    re-stamped with ``pid = <track index>`` plus a ``process_name``
    metadata event naming the host, and shifted onto the fleet timebase
    via the snapshot clock anchors (hosts without a snapshot stay on
    their own zero — visible, just unaligned).  Returns the event list
    sorted by ``ts``; :func:`write_merged_trace` writes it in the JSON
    array form Perfetto loads directly.
    """
    anchors = {f"{s['host']}_{s['pid']}":
               float(s.get("clock", {}).get("trace_t0_unix", 0.0))
               for s in read_snapshots(run_dir)}
    known = [v for v in anchors.values() if v > 0]
    fleet_t0 = min(known) if known else 0.0
    merged: List[Dict] = []
    meta: List[Dict] = []
    for track, path in enumerate(_span_files(run_dir)):
        stem = _stem_of(path)
        shift_us = (anchors.get(stem, fleet_t0) - fleet_t0) * 1e6
        meta.append({"name": "process_name", "ph": "M", "pid": track,
                     "tid": 0, "args": {"name": stem}})
        try:
            with open(path) as f:
                lines = f.read().splitlines()
        except OSError:
            continue
        for line in lines:
            if not line.strip():
                continue
            try:
                ev = json.loads(line)
            except ValueError:
                continue  # torn tail write on a crashed host
            ev["pid"] = track
            ev["ts"] = round(float(ev.get("ts", 0.0)) + shift_us, 3)
            merged.append(ev)
    merged.sort(key=lambda e: e.get("ts", 0.0))
    return meta + merged


def write_merged_trace(run_dir: str,
                       out_path: Optional[str] = None) -> str:
    """Write :func:`merge_traces` output as ``fleet_trace.json`` (JSON
    array — loadable in Perfetto / chrome://tracing as-is)."""
    events = merge_traces(run_dir)
    if out_path is None:
        out_path = os.path.join(telemetry_dir(run_dir), "fleet_trace.json")
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(events, f)
    os.replace(tmp, out_path)
    return out_path


def _percentile(vals: List[float], q: float) -> float:
    """Nearest-rank percentile over a small host population."""
    vals = sorted(vals)
    idx = min(len(vals) - 1, max(0, int(round(q * (len(vals) - 1)))))
    return vals[idx]


def fleet_rollup(run_dir: str) -> Dict:
    """sum/max/p50/p99 of every numeric registry metric across hosts.

    Counters roll up meaningfully as ``sum`` (fleet totals), gauges as
    ``max``/percentiles (stragglers); the rollup reports all four per
    key and lets the consumer pick, because the snapshot is a flat
    scalar dict with no type tags.
    """
    snaps = read_snapshots(run_dir)
    per_key: Dict[str, List[float]] = {}
    for s in snaps:
        for k, v in (s.get("metrics") or {}).items():
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            per_key.setdefault(k, []).append(float(v))
    rollup = {
        k: {"sum": sum(vals), "max": max(vals),
            "p50": _percentile(vals, 0.50),
            "p99": _percentile(vals, 0.99),
            "n_hosts": len(vals)}
        for k, vals in sorted(per_key.items())}
    # pod shard attribution: which SPMD process each snapshot belongs
    # to, its own accepted total, and the collective time it burned in
    # host-side cross-process syncs (wire_collective_seconds_total —
    # zero in the one-dispatch steady state, by contract)
    hosts = []
    gens = 0
    collective_s = 0.0
    for s in snaps:
        m = s.get("metrics") or {}
        hb = s.get("heartbeat") or {}
        pod = s.get("pod") or {}
        c = float(m.get("wire_collective_seconds_total", 0.0))
        collective_s += c
        gens = max(gens, int(hb.get("generations", 0)))
        hosts.append({"host": s["host"], "pid": s["pid"],
                      "process_index": pod.get("process_index",
                                               s.get("process_index")),
                      "accepted": int(hb.get("accepted", 0)),
                      "collective_s": c,
                      "written_unix": s.get("written_unix"),
                      "run_progress": s.get("run_progress")})
    pod_hosts = max([int((s.get("pod") or {}).get("process_count", 1))
                     for s in snaps] or [1])
    return {"n_hosts": len(snaps),
            "pod_hosts": pod_hosts,
            "collective_s_per_gen": collective_s / gens if gens else 0.0,
            "hosts": hosts,
            # the fleet-merged in-dispatch progress word (lanes.py):
            # pod processes run in lockstep, so one word speaks for all
            "run_progress": merge_progress(
                [s.get("run_progress") for s in snaps]),
            "serve": _serve_rollup(rollup),
            "sched": _sched_rollup(rollup),
            "metrics": rollup}


#: serve_* keys that are point-in-time gauges — fleet view reads their
#: max; everything else under serve_* is a counter and rolls up as sum
_SERVE_GAUGES = frozenset({
    "serve_queue_depth", "serve_engines_warm", "serve_cache_hit_ratio",
    "serve_cache_hit_ratio_t1", "serve_cache_hit_ratio_t2",
    "serve_last_study_ms", "serve_drain_requeued",
    "serve_partitions", "serve_partition_depth_max",
    "serve_slo_p99_ms",
})


def is_serve_gauge(key: str) -> bool:
    """Whether a ``serve_*`` metric is a point-in-time gauge (fleet
    max) rather than a counter (fleet sum).  Per-partition depth
    gauges (``serve_partition_p<NNNN>_depth``) are name-generated, so
    they match by shape rather than by set membership."""
    return (key in _SERVE_GAUGES
            or (key.startswith("serve_partition_p")
                and key.endswith("_depth")))


def _serve_rollup(metrics_rollup: Dict) -> Dict:
    """The serving tier's slice of the fleet rollup: every ``serve_*``
    metric collapsed to one number (counters summed across workers,
    gauges maxed), plus the per-tenant attribution table."""
    out: Dict = {}
    tenants: Dict[str, float] = {}
    for key, aggs in metrics_rollup.items():
        if not key.startswith("serve_"):
            continue
        val = aggs["max" if is_serve_gauge(key) else "sum"]
        out[key] = val
        if key.startswith("serve_tenant_") and key.endswith(
                "_studies_total"):
            tenants[key[len("serve_tenant_"):-len("_studies_total")]] \
                = val
    out["tenants"] = tenants
    # the study-trace accounting (telemetry/studytrace.py): re-fold
    # the flat per-bucket counters into fleet latency histograms and
    # the SLO burn ledger — bucket counters sum across workers, so
    # the fleet histogram is exact, not an average of percentiles
    from . import studytrace
    if any(k.startswith("serve_latency_ms_") for k in out):
        out["latency"] = studytrace.latency_histogram(
            out, "serve_latency_ms")
        out["queue_wait"] = studytrace.latency_histogram(
            out, "serve_queue_wait_ms")
        out["slo"] = studytrace.slo_ledger(out)
    return out


#: sched_* keys that are point-in-time gauges — fleet view reads their
#: max; everything else under sched_* is a counter and rolls up as sum
_SCHED_GAUGES = frozenset({
    "sched_workers_alive", "sched_workers_dead",
    "sched_desired_replicas", "sched_queue_pending",
    "sched_queue_claimed", "sched_oldest_pending_s",
    "sched_last_tick_ms", "sched_platform_replicas",
})


def _sched_rollup(metrics_rollup: Dict) -> Dict:
    """The scheduler's slice of the fleet rollup: every ``sched_*``
    metric collapsed to one number (counters summed across scheduler
    replicas, gauges maxed) — the control-plane mirror of
    :func:`_serve_rollup`."""
    out: Dict = {}
    for key, aggs in metrics_rollup.items():
        if not key.startswith("sched_"):
            continue
        out[key] = aggs["max" if key in _SCHED_GAUGES else "sum"]
    return out


def render_prometheus(run_dir: str) -> str:
    """The fleet rollup as Prometheus text: each metric exported as
    ``pyabc_tpu_fleet_<key>{agg="sum|max|p50|p99"}`` samples plus a
    ``pyabc_tpu_fleet_hosts`` gauge — the scrape surface for a whole
    run directory, complementing the per-worker exporter."""
    roll = fleet_rollup(run_dir)
    lines = [f"pyabc_tpu_fleet_hosts {roll['n_hosts']}",
             f"pyabc_tpu_fleet_pod_hosts {roll['pod_hosts']}",
             "pyabc_tpu_fleet_collective_s_per_gen "
             f"{roll['collective_s_per_gen']}"]
    prog = roll.get("run_progress")
    if prog is not None:
        lines += [
            "pyabc_tpu_fleet_run_progress_active "
            f"{1 if prog.get('active') else 0}",
            f"pyabc_tpu_fleet_run_progress_gen {prog.get('gen', 0)}",
            "pyabc_tpu_fleet_run_progress_gens_done "
            f"{prog.get('gens_done', 0)}",
            "pyabc_tpu_fleet_run_progress_rounds "
            f"{prog.get('rounds', 0)}",
        ]
    # the serving tier's first-class scrape surface: flat
    # ``pyabc_tpu_serve_*`` gauges (tenant counters already carry the
    # tenant in the key), alongside the generic fleet aggregates below
    serve = roll.get("serve") or {}
    for key, val in sorted(serve.items()):
        if key in ("tenants", "latency", "queue_wait", "slo"):
            continue  # structured blocks: rendered below / JSON-only
        if (key.endswith("_sum_total") or "_ms_le_" in key):
            continue  # flat bucket counters: rendered as histograms
        lines.append(f"pyabc_tpu_{key} {val}")
    # the per-bucket latency counters re-assembled into real
    # Prometheus histogram exposition (cumulative le labels)
    for name in ("serve_latency_ms", "serve_queue_wait_ms"):
        hist = serve.get("latency" if name == "serve_latency_ms"
                         else "queue_wait")
        if not hist or not hist.get("count"):
            continue
        for le, n in hist["buckets"].items():
            lines.append(
                f'pyabc_tpu_{name}_bucket{{le="{le}"}} {n}')
        lines.append(
            f'pyabc_tpu_{name}_bucket{{le="+Inf"}} {hist["count"]}')
        lines.append(f"pyabc_tpu_{name}_sum {hist['sum_ms']}")
        lines.append(f"pyabc_tpu_{name}_count {hist['count']}")
    # the scheduler's scrape surface: flat ``pyabc_tpu_sched_*`` lines
    # (workers alive/dead, leases lapsed, requeues, quarantines,
    # desired replicas) from the same snapshot rollup
    for key, val in sorted((roll.get("sched") or {}).items()):
        lines.append(f"pyabc_tpu_{key} {val}")
    for key, aggs in roll["metrics"].items():
        for agg in ("sum", "max", "p50", "p99"):
            lines.append(
                f'pyabc_tpu_fleet_{key}{{agg="{agg}"}} {aggs[agg]}')
    return "\n".join(lines) + "\n"
