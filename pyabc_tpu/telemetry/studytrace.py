"""Study-trace assembly: lifecycle events → critical-path attribution.

The serving data plane appends one structured event per study state
transition to ``<serve root>/trace/`` (:mod:`pyabc_tpu.serve.tracing`).
This module is the READ side: it folds an event stream into the
study's critical path — where, inside one study's life, the time went:

========================  =============================================
phase                     interval
========================  =============================================
``queue_wait_s``          every ``submitted``/``requeued`` → next
                          ``claimed`` interval, SUMMED across bounces
``claim_to_dispatch_s``   ``claimed`` → ``batched`` (spec unpickle,
                          cache probe, batch grouping)
``compile_s``             ``batched`` → ``dispatched`` (engine build /
                          renew, study-axis program build)
``device_s``              ``dispatched`` → ``drained`` (the dispatch
                          itself, result fetch included)
``drain_s``               ``drained`` → ``published`` (summary
                          assembly + cache publish)
``publish_s``             ``published`` → ``tombstoned`` (tombstone
                          write; also the tail phase of a cache hit)
========================  =============================================

Phases are derived from consecutive event timestamps of ONE ordered
stream, so they are monotone and non-overlapping by construction, and
they sum to the study's end-to-end latency (tombstone minus submit) —
the property ``bench_serve_load`` checks against the load generator's
client-observed latency (the residual gap is the client's tombstone
poll interval, reported, never hidden).

Timestamps are event ``unix`` clocks: a trace spans workers (a bounced
study's events come from several processes/hosts), so cross-process
wall clocks — accurate to the fleet's NTP agreement — are the only
common timebase, exactly like the span merger's clock anchors.

Also here: the fleet-wide latency HISTOGRAM counters and the SLO burn
ledger.  Snapshots flatten registry histograms to ``_count``/``_sum``,
so per-bucket detail would die at the snapshot boundary; instead each
bucket is a flat counter (``serve_latency_ms_le_<bucket>``) that rolls
up across workers as a plain sum, and ``aggregate.render_prometheus``
re-assembles the buckets into a real Prometheus histogram
(``pyabc_tpu_serve_latency_ms_bucket{le="..."}``).

Import direction: telemetry is a LEAF package — this module reads the
trace directory with plain ``os``/``json`` and imports nothing from
``pyabc_tpu.serve``.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional

from . import spans
from .metrics import REGISTRY

#: critical-path phase names, in lifecycle order
PHASES = ("queue_wait_s", "claim_to_dispatch_s", "compile_s",
          "device_s", "drain_s", "publish_s")

#: the phase a given event OPENS (closing whatever phase was open);
#: events absent here (queued, rescued, shed, rejected) mark instants
#: but do not move the phase machine
_OPENS = {
    "submitted": "queue_wait_s",
    "requeued": "queue_wait_s",
    "claimed": "claim_to_dispatch_s",
    "cache_hit": "publish_s",
    "batched": "compile_s",
    "dispatched": "device_s",
    "drained": "drain_s",
    "published": "publish_s",
}

#: latency histogram bucket upper bounds (milliseconds); flat counters
#: named ``<name>_le_<bucket>`` + ``<name>_le_inf`` + ``<name>_sum_total``
LATENCY_BUCKETS_MS = (5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
                      1000.0, 2500.0, 5000.0, 10000.0)

#: the serve-root subdirectory the event log lives in (mirrors
#: serve/tracing.py without importing it — telemetry stays a leaf)
_TRACE_SUBDIR = "trace"


# ---- folding ------------------------------------------------------------

def fold_segments(events: List[dict],
                  end_unix: Optional[float] = None) -> List[dict]:
    """Fold an ordered event stream into contiguous phase segments
    ``[{"phase", "t0_unix", "dur_s"}, ...]``.

    Each event closes the open phase at its timestamp and (if it is a
    phase-opening event) starts the next — one ordered walk, so
    segments never overlap and cover submit → tombstone exactly.  A
    ``tombstoned`` event (or ``end_unix``) closes the final phase."""
    evs = sorted(events, key=lambda r: (float(r.get("unix", 0.0)),
                                        float(r.get("mono", 0.0))))
    segments: List[dict] = []
    open_phase: Optional[str] = None
    open_t0 = 0.0

    def _close(at: float):
        nonlocal open_phase
        if open_phase is not None:
            segments.append({"phase": open_phase, "t0_unix": open_t0,
                             "dur_s": max(at - open_t0, 0.0)})
            open_phase = None

    for rec in evs:
        name = rec.get("event")
        unix = float(rec.get("unix", 0.0))
        if name == "tombstoned":
            _close(unix)
            continue
        opens = _OPENS.get(name)
        if opens is None:
            continue  # instant marker (queued, rescued, shed, ...)
        _close(unix)
        open_phase, open_t0 = opens, unix
    if end_unix is not None:
        _close(float(end_unix))
    return segments


def fold_phases(events: List[dict],
                end_unix: Optional[float] = None) -> dict:
    """Per-phase totals (every :data:`PHASES` key present, seconds),
    plus ``total_s``, ``bounces`` and ``events_n`` — the critical-path
    block written into done/failed tombstones."""
    segments = fold_segments(events, end_unix=end_unix)
    phases = {p: 0.0 for p in PHASES}
    for seg in segments:
        phases[seg["phase"]] = round(
            phases[seg["phase"]] + seg["dur_s"], 6)
    first = min((float(r.get("unix", 0.0)) for r in events
                 if r.get("event") in _OPENS), default=0.0)
    last = (float(end_unix) if end_unix is not None
            else max((float(r.get("unix", 0.0)) for r in events),
                     default=first))
    phases["total_s"] = round(max(last - first, 0.0), 6) if first else 0.0
    phases["bounces"] = sum(1 for r in events
                            if r.get("event") == "requeued")
    phases["events_n"] = len(events)
    return phases


# ---- assembly -----------------------------------------------------------

def _scan_trace_dir(serve_root: str) -> Iterator[dict]:
    """Every parseable event under ``<serve root>/trace/`` —
    torn-tail tolerant (unparseable lines are a crashed emitter's
    last write, skipped)."""
    root = os.path.join(serve_root, _TRACE_SUBDIR)
    try:
        parts = sorted(os.listdir(root))
    except OSError:
        return
    for part in parts:
        pdir = os.path.join(root, part)
        try:
            names = sorted(os.listdir(pdir))
        except OSError:
            continue
        for name in names:
            if not name.endswith(".jsonl"):
                continue
            try:
                with open(os.path.join(pdir, name),
                          encoding="utf-8") as f:
                    lines = f.read().splitlines()
            except OSError:
                continue
            for line in lines:
                if not line.strip():
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict):
                    yield rec


@dataclass
class StudyTrace:
    """One assembled study trace: the ordered event stream plus its
    folded critical path."""

    trace_id: str
    ticket: Optional[str] = None
    digest: Optional[str] = None
    events: List[dict] = field(default_factory=list)
    phases: Dict[str, float] = field(default_factory=dict)

    @property
    def workers(self) -> List[str]:
        """Every worker that touched this study, in event order —
        length > 1 means the trace is continuous across a bounce."""
        seen: List[str] = []
        for rec in self.events:
            w = rec.get("worker")
            if w and w not in seen:
                seen.append(w)
        return seen

    def event_names(self) -> List[str]:
        return [str(r.get("event")) for r in self.events]

    # -- export --------------------------------------------------------

    def to_chrome_events(self) -> List[dict]:
        """Chrome-trace complete events: one ``"X"`` span per folded
        lifecycle phase segment (plus one instant event per raw
        lifecycle event), on a unix-anchored microsecond timebase —
        loads in Perfetto directly and merges with the fleet span
        tracks (``aggregate.merge_traces`` aligns hosts onto the same
        unix anchor)."""
        if not self.events:
            return []
        t0 = min(float(r.get("unix", 0.0)) for r in self.events)
        end = max(float(r.get("unix", 0.0)) for r in self.events)
        out = [{"name": "process_name", "ph": "M", "pid": 0, "tid": 0,
                "args": {"name": f"study {self.ticket or self.trace_id}"}}]
        for seg in fold_segments(self.events, end_unix=end):
            out.append(spans.complete_event(
                f"study.{seg['phase'][:-2]}",
                ts_us=(seg["t0_unix"] - t0) * 1e6,
                dur_us=seg["dur_s"] * 1e6,
                args={"trace_id": self.trace_id}))
        for rec in self.events:
            ev = {"name": f"event.{rec.get('event')}",
                  "cat": "pyabc_tpu", "ph": "i", "s": "t",
                  "ts": round((float(rec.get("unix", 0.0)) - t0) * 1e6,
                              3),
                  "pid": 0, "tid": 0,
                  "args": {k: v for k, v in rec.items()
                           if k not in ("unix", "mono")}}
            out.append(ev)
        return out

    def write_chrome_trace(self, path: str) -> str:
        """The trace as a Chrome-trace JSON array file."""
        events = self.to_chrome_events()
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(events, f)
        os.replace(tmp, path)
        return path

    def to_dict(self) -> dict:
        return {"trace_id": self.trace_id, "ticket": self.ticket,
                "digest": self.digest, "workers": self.workers,
                "events": self.events, "phases": self.phases}

    # -- construction --------------------------------------------------

    @classmethod
    def from_events(cls, events: List[dict],
                    end_unix: Optional[float] = None) -> "StudyTrace":
        evs = sorted(events, key=lambda r: (float(r.get("unix", 0.0)),
                                            float(r.get("mono", 0.0))))
        trace_id = next((r.get("trace_id") for r in evs
                         if r.get("trace_id")), "")
        ticket = next((r.get("ticket") for r in evs
                       if r.get("ticket")), None)
        digest = next((r.get("digest") for r in evs
                       if r.get("digest")), None)
        return cls(trace_id=str(trace_id), ticket=ticket, digest=digest,
                   events=evs, phases=fold_phases(evs,
                                                  end_unix=end_unix))

    @classmethod
    def assemble(cls, serve_root: str,
                 key: str) -> Optional["StudyTrace"]:
        """Assemble ONE study's trace from the serve root's event log,
        looked up by trace id, ticket id, or digest (the newest
        matching trace when a digest key matches several).  ``None``
        when nothing matches."""
        traces = cls.assemble_all(serve_root, key)
        return traces[-1] if traces else None

    @classmethod
    def assemble_all(cls, serve_root: str,
                     key: str) -> List["StudyTrace"]:
        """Every trace matching ``key``, oldest first."""
        by_trace: Dict[str, List[dict]] = {}
        for rec in _scan_trace_dir(serve_root):
            if key in (rec.get("trace_id"), rec.get("ticket"),
                       rec.get("digest")):
                tid = str(rec.get("trace_id", ""))
                by_trace.setdefault(tid, []).append(rec)
        traces = [cls.from_events(evs) for evs in by_trace.values()]
        traces.sort(key=lambda t: min(
            (float(r.get("unix", 0.0)) for r in t.events), default=0.0))
        return traces


# ---- fleet accounting ---------------------------------------------------

def observe_latency_ms(name: str, ms: float):
    """Record one observation into the flat-bucket histogram counters
    (cumulative Prometheus ``le`` semantics; rolled back into a real
    histogram by ``aggregate.render_prometheus``)."""
    for b in LATENCY_BUCKETS_MS:
        if ms <= b:
            REGISTRY.counter(
                f"{name}_le_{b:g}",
                f"{name} observations <= {b:g} ms").inc()
    REGISTRY.counter(f"{name}_le_inf",
                     f"{name} observations (all)").inc()
    REGISTRY.counter(f"{name}_sum_total",
                     f"{name} summed milliseconds").inc(max(ms, 0.0))


def record_study_slo(e2e_ms: float, queue_wait_ms: float,
                     slo_p99_ms: Optional[float] = None):
    """One served study's latency accounting: the fleet latency and
    queue-wait histograms, plus the SLO burn ledger when an SLO is
    configured — ``over`` is burned budget, ``under`` is headroom;
    sheds are counted at admission (``serve_shed_total``), the
    shed-instead-of-burned side of the ledger."""
    observe_latency_ms("serve_latency_ms", e2e_ms)
    observe_latency_ms("serve_queue_wait_ms", queue_wait_ms)
    if not slo_p99_ms or slo_p99_ms <= 0:
        return
    REGISTRY.gauge(
        "serve_slo_p99_ms",
        "configured end-to-end latency SLO"
    ).set(float(slo_p99_ms))
    if e2e_ms > slo_p99_ms:
        REGISTRY.counter(
            "serve_slo_over_total",
            "admitted studies that finished OVER the latency SLO "
            "(burned budget)").inc()
    else:
        REGISTRY.counter(
            "serve_slo_under_total",
            "admitted studies that finished within the latency SLO"
        ).inc()


def latency_histogram(rollup_serve: Dict[str, float],
                      name: str = "serve_latency_ms") -> dict:
    """Re-assemble one flat-bucket histogram from a serve rollup
    block: ``{"buckets": {"5": n, ...}, "count", "sum_ms", "p50_ms",
    "p99_ms"}`` (percentiles are bucket-upper-bound estimates)."""
    buckets = {}
    for b in LATENCY_BUCKETS_MS:
        key = f"{name}_le_{b:g}"
        if key in rollup_serve:
            buckets[f"{b:g}"] = float(rollup_serve[key])
    count = float(rollup_serve.get(f"{name}_le_inf", 0.0))
    total = float(rollup_serve.get(f"{name}_sum_total", 0.0))

    def _pct(q: float) -> float:
        if count <= 0:
            return 0.0
        rank = q * count
        for b in LATENCY_BUCKETS_MS:
            if buckets.get(f"{b:g}", 0.0) >= rank:
                return float(b)
        return float("inf")

    return {"buckets": buckets, "count": count,
            "sum_ms": round(total, 3),
            "p50_ms": _pct(0.50), "p99_ms": _pct(0.99)}


def slo_ledger(rollup_serve: Dict[str, float]) -> dict:
    """The fleet SLO burn ledger from a serve rollup block: admitted
    studies over/under the SLO, sheds (rejected instead of burned),
    and the burn rate over admitted completions."""
    over = float(rollup_serve.get("serve_slo_over_total", 0.0))
    under = float(rollup_serve.get("serve_slo_under_total", 0.0))
    shed = float(rollup_serve.get("serve_shed_total", 0.0))
    admitted = over + under
    return {
        "slo_p99_ms": float(rollup_serve.get("serve_slo_p99_ms", 0.0)),
        "over": over, "under": under, "shed": shed,
        "burn_rate": round(over / admitted, 5) if admitted else 0.0,
    }


def waterfall_text(trace: StudyTrace, width: int = 48) -> List[str]:
    """The trace as an ASCII latency waterfall (the ``abc-top
    --study`` view): one bar per phase, scaled to the study's total
    wall clock."""
    phases = trace.phases or {}
    total = max(float(phases.get("total_s", 0.0)), 1e-9)
    lines = [f"study {trace.ticket or trace.trace_id}  "
             f"total {total * 1e3:.1f}ms  "
             f"bounces {int(phases.get('bounces', 0))}  "
             f"workers {','.join(trace.workers) or '-'}"]
    offset = 0.0
    for p in PHASES:
        dur = float(phases.get(p, 0.0))
        pad = int(round(width * offset / total))
        bar = max(int(round(width * dur / total)), 1 if dur > 0 else 0)
        lines.append(f"  {p:<20s} {dur * 1e3:>9.1f}ms "
                     f"|{' ' * pad}{'#' * bar}")
        offset += dur
    return lines


def now_unix() -> float:
    """Indirection point for tests that freeze the fold clock."""
    return time.time()
