"""CI smoke for the north-star posterior-exactness gate
(tools/verify_northstar_posterior.py; VERDICT r4 next #6).

The driver-grade gate runs pop 1e6 on the chip inside bench.py; here the
same code path runs a small population on the CPU mesh so a statistical
regression in the fast paths (wire narrowing, deferred proposal, device
supports) is caught by the ordinary test suite.
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

from verify_northstar_posterior import run_gate  # noqa: E402


def test_gate_smoke_small_pop():
    out = run_gate(pop=20_000, gens=6, seed=0)
    assert out["posterior_gate_ok"], out
    # epsilon must actually have annealed (the gate exercises refits)
    assert out["posterior_gate_final_eps"] < 0.1, out


@pytest.mark.slow
@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_gate_multi_seed_pop_1e5(seed):
    """Driver-grade seed sweep: the full 11-generation gate at pop 1e5
    across >= 4 seeds.  Four independent passes at 1/sqrt(pop)-scaled
    tolerance make a systematic bias in the fast paths (fused blocks,
    capped-support refit, wire narrowing, deferred proposal) detectable
    where the single-seed smoke above could ride seed weather."""
    out = run_gate(pop=100_000, gens=11, seed=seed)
    assert out["posterior_gate_ok"], out
    assert out["posterior_gate_final_eps"] < 0.05, out
