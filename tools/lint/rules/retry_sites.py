"""Rule ``retry-sites``: hot-loop device dispatches route through the
retry policy.

``pyabc_tpu/resilience/retry.py`` wraps every device dispatch and the
d2h chokepoint in bounded-backoff retry with transient-vs-fatal
classification.  A raw call to one of the sampler's compiled loop
functions (``step``/``finalize``/...) or the orchestrator's block
function bypasses that policy: a transient relay/runtime hiccup then
kills the whole run instead of costing one backoff — and the
``resilience_*`` telemetry under-reports.

Checks (manifest-scoped: only the files that own dispatch sites):

- ``sampler/vectorized.py``: any direct call of a stateful-loop
  function must go through ``self._dispatch(...)``;
- ``smc.py``: the fused/pipelined block dispatch ``fn(carry_in, ...)``
  must go through ``self._retry.call(...)``;
- ``sampler/base.py`` must still route ``fetch_to_host`` through the
  shared retry policy at the ``SITE_FETCH`` site.

Legacy suppression: ``# retry-ok`` on the line;
``# graftlint: allow(retry-sites)`` also works.
"""

from __future__ import annotations

import os
import re
import sys

from ..core import Finding, Rule, default_package_root, register

SUPPRESS = "# retry-ok"

#: relpath (package root, forward slashes) -> raw-dispatch smell
MANIFEST = {
    "sampler/vectorized.py": re.compile(
        r"\b(?:step_finalize|step|finalize|harvest|start|reset)\s*\("),
    "smc.py": re.compile(r"\bfn\s*\(\s*carry_in"),
}

#: a smelly line is clean when the call is routed through either wrapper
_ROUTED = ("_dispatch(", "_retry.call(")

#: the d2h chokepoint must keep its retry wrapper: both markers present
CHOKEPOINT_FILE = "sampler/base.py"
CHOKEPOINT_MARKERS = ("SITE_FETCH", "shared_policy")


def _package_root(root: str = None) -> str:
    return root if root is not None else default_package_root()


def check(root: str = None) -> list:
    """Scan the manifest files; returns ``[(relpath, lineno, line), ...]``
    violations (empty = clean)."""
    root = _package_root(root)
    violations = []
    for rel, smell in MANIFEST.items():
        path = os.path.join(root, rel.replace("/", os.sep))
        if not os.path.exists(path):
            continue  # planted-tree tests cover subsets of the manifest
        with open(path, encoding="utf-8") as f:
            for lineno, line in enumerate(f, 1):
                if SUPPRESS in line:
                    continue
                code = line.split("#", 1)[0]
                if not smell.search(code):
                    continue
                if any(marker in code for marker in _ROUTED):
                    continue
                violations.append((rel, lineno, line.rstrip()))
    chokepoint = os.path.join(root, CHOKEPOINT_FILE.replace("/", os.sep))
    if os.path.exists(chokepoint):
        with open(chokepoint, encoding="utf-8") as f:
            text = f.read()
        for marker in CHOKEPOINT_MARKERS:
            if marker not in text:
                violations.append((
                    CHOKEPOINT_FILE, 0,
                    f"fetch_to_host lost its retry wrapper (no "
                    f"{marker!r} in the file)"))
    return violations


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    root = argv[0] if argv else None
    violations = check(root)
    if not violations:
        print("retry sites: clean (all hot-loop dispatches route "
              "through resilience/retry.py)")
        return 0
    print("retry-site violations (route dispatches through "
          "self._dispatch / self._retry.call, or justify with "
          f"'{SUPPRESS}'):")
    for rel, lineno, line in violations:
        print(f"  pyabc_tpu/{rel}:{lineno}: {line.strip()}")
    return 1


@register
class RetrySitesRule(Rule):
    id = "retry-sites"
    description = ("hot-loop device dispatches route through "
                   "resilience/retry.py wrappers")

    def run(self, tree):
        prefix = tree.package_rel_prefix()
        return [Finding(self.id, f"{prefix}/{rel}", lineno,
                        line.strip() if lineno else line)
                for rel, lineno, line in check(tree.package_root)]
