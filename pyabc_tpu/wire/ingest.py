"""Wire-payload decode + population assembly, shared by every ingest
site: the fused K-generation single-transaction fetch, the overlapped
streaming pipeline, and the sequential fallback with a deferred wire.

These are the host halves of the codec seam (``narrow_wire`` on device,
``widen_wire`` here) plus the log-space weight normalization every
History append needs.  Keeping one copy means the overlapped-vs-
sequential exactness guarantee is structural: both modes decode through
the same functions in the same order.

Imports from the sampler package are function-local — ``wire`` is a
leaf package the sampler itself depends on (for the transfer ledger),
so module-level imports here would cycle.
"""

from __future__ import annotations

import numpy as np

_SCALAR_KEYS = ("count", "rounds", "eps")


def split_block_wire(wires: dict, K: int, n: int):
    """Split a fetched K-generation stacked wire into per-generation
    widened batches plus the scalar lanes.

    Returns ``(gens, counts, rounds, eps_vals)`` where ``gens[k]`` is
    the widened host batch of generation ``k`` (keys ``m``/``theta``/
    ``distance``/``log_weight`` and optionally ``stats``, ``n`` rows)
    and the other three are length-``K`` arrays (``eps_vals`` is None
    when the wire carries no eps lane).
    """
    from ..sampler.base import widen_wire

    counts = np.asarray(wires["count"]).reshape(K)
    rounds = np.asarray(wires["rounds"]).reshape(K)
    eps_vals = (np.asarray(wires["eps"], dtype=np.float64).reshape(K)
                if "eps" in wires else None)
    gens = [widen_wire({key: v[k] for key, v in wires.items()
                        if key not in _SCALAR_KEYS}, n)
            for k in range(K)]
    return gens, counts, rounds, eps_vals


def split_gen_wire(out: dict, n: int):
    """Decode ONE generation's slice of a fused block wire
    (``sampler.device_loop.slice_block_wire``) into
    ``(batch, count, rounds, eps)`` — the per-``k`` unit of
    :func:`split_block_wire`, for streamed per-generation block fetch.
    ``eps`` is None when the wire carries no eps lane."""
    from ..sampler.base import widen_wire

    batch = widen_wire({key: v for key, v in out.items()
                        if key not in _SCALAR_KEYS}, n)
    count = int(np.asarray(out["count"]))
    rounds = int(np.asarray(out["rounds"]))
    eps = (float(np.asarray(out["eps"], dtype=np.float64))
           if "eps" in out else None)
    return batch, count, rounds, eps


class GenStream:
    """Streamed per-generation fetch of one K-generation block wire.

    At most ONE sub-ticket is in flight per block: :meth:`result`
    resolves generation ``k`` and immediately submits generation
    ``k+1``'s fetch+decode, so the next generation's d2h drains on the
    ingest worker while the caller decodes/appends the current one (and,
    in the pipelined path, while later blocks compute on device).  The
    one-ahead discipline is what makes the streams composable with
    ``StreamingIngest``'s depth backpressure: a block never holds more
    than one of the engine's depth slots, so ``depth`` blocks can stream
    concurrently without the submit() semaphore deadlocking against its
    own unharvested tickets.
    """

    def __init__(self, engine, wires: dict, K: int, n: int, label: str,
                 fetch=None):
        self._engine = engine
        self._wires = wires
        self._K = K
        self._n = n
        self._label = label
        #: optional replacement for the default fetch+decode, signature
        #: ``fetch(k, gen_wire, n) -> (payload, count, rounds, eps)`` —
        #: the lazy-History path uses it to deposit the full slice into
        #: the DeviceRunStore and ship only the O(KB) summary lanes
        #: (``payload`` is then the summary packet, not a batch).
        #: ``drain_rounds``/``result`` only rely on the tuple layout.
        self._fetch = fetch
        self._next = 0
        self._ticket = None
        self._span = None
        self._submit()

    def _submit(self):
        from ..telemetry import spans
        if self._next >= self._K:
            self._ticket = None
            self._span = None
            self._wires = None  # release the device block reference
            return
        from ..sampler.device_loop import slice_block_wire
        k = self._next
        gw = slice_block_wire(self._wires, k)
        # one stream.gen span per in-flight generation, explicitly ended
        # on EVERY resolution path (result/drain_rounds/abandon) so a
        # Perfetto trace of an early-stopped or rewound block has no
        # dangling begins (tools/check_span_pairs.py)
        self._span = spans.begin("stream.gen", gen=k, label=self._label)
        if self._fetch is not None:
            fn = (lambda f=self._fetch, k=k, gw=gw, n=self._n:
                  f(k, gw, n))
        else:
            fn = (lambda gw=gw, n=self._n: _fetch_gen(gw, n))
        self._ticket = self._engine.submit(fn, label=f"{self._label}+{k}")
        self._next += 1

    def _end_span(self, outcome: str):
        from ..telemetry import spans
        if self._span is not None:
            spans.end(self._span.set(outcome=outcome))
            self._span = None

    def result(self):
        """Resolve the next generation's ``(batch, count, rounds, eps)``
        and queue the following one."""
        try:
            out = self._ticket.result()
        finally:
            self._end_span("resolved")
        self._submit()
        return out

    def drain_rounds(self) -> int:
        """Resolve every remaining generation for its ``rounds`` scalar
        only — exact simulation accounting after an early stop inside
        the block (the stopped-past generations still simulated)."""
        total = 0
        while self._ticket is not None:
            try:
                _, _, rounds, _ = self._ticket.result()
                total += int(rounds)
            except Exception:
                pass  # a failed tail fetch only loses accounting
            self._end_span("drained")
            self._submit()
        return total

    def abandon(self):
        """Drop the stream (pipelined rewind): the in-flight ticket is
        abandoned, unsubmitted generations never fetch."""
        if self._ticket is not None:
            self._ticket.abandon()
            self._ticket = None
        self._end_span("abandoned")
        self._wires = None


def _fetch_gen(gen_wire: dict, n: int):
    from ..sampler.base import fetch_to_host

    return split_gen_wire(fetch_to_host(gen_wire), n)


def split_single_wire(out: dict, n: int):
    """Decode a single-generation deferred wire (the per-generation
    sampler's finalize payload) into the same shape as
    :func:`split_block_wire` with ``K == 1``."""
    from ..sampler.base import widen_wire

    batch = widen_wire({key: v for key, v in out.items()
                        if key not in _SCALAR_KEYS}, n)
    counts = np.asarray([out["count"]]).reshape(1)
    rounds = (np.asarray([out["rounds"]]).reshape(1)
              if "rounds" in out else None)
    return [batch], counts, rounds, None


def batch_to_population(batch: dict):
    """Normalize the shift-encoded log weights and build a
    :class:`~pyabc_tpu.population.Population`; returns ``None`` when the
    weights are degenerate (all -inf / NaN — callers fall back or fail
    loudly, matching the pre-wire fused-block behavior)."""
    from ..population import Population

    lw = np.asarray(batch["log_weight"], dtype=np.float64)
    lw = lw - lw.max()
    w = np.exp(lw)
    w_sum = w.sum()
    if not (np.isfinite(w_sum) and w_sum > 0):
        return None
    return Population(
        m=batch["m"], theta=batch["theta"],
        weight=(w / w_sum).astype(np.float32),
        distance=batch["distance"],
        sum_stats=({"__flat__": batch["stats"]}
                   if "stats" in batch else {}),
    )
