"""Sampler contract + the Sample accumulator.

Parity: pyabc/sampler/base.py (233 LoC).  The reference contract is

    sampler.sample_until_n_accepted(n, simulate_one, ...) -> Sample

where ``simulate_one`` is a per-particle closure farmed out to processes.
The TPU contract replaces the closure with a *compiled round function*

    round_fn(key, params) -> RoundResult   (a fixed-shape batch of B
                                            candidate particles)

and ``sample_until_n_accepted`` becomes a host-controlled loop of
over-provisioned fixed-shape rounds (SURVEY.md §7): simulate B ≥ n
candidates, mask-accept, accumulate, repeat.  Because rounds are
deterministic in submission order, the reference's sort-by-id + truncate
de-biasing protocol (multicore_evaluation_parallel.py:134-136,
redis_eps/sampler.py:141-144) is satisfied trivially: accepted particles
are concatenated in round order and truncated to the first n.

``nr_evaluations_`` bookkeeping matches sampler/base.py:189 (= rounds × B).
The output-size assertion of ``SamplerMeta`` (base.py:144-169) lives in
:meth:`Sample.get_accepted_population`.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from ..population import Population

Array = jnp.ndarray


class RoundResult:
    """One fixed-shape batch of candidates (a pytree of arrays)."""

    def __init__(self, m, theta, distance, accepted, log_weight, stats,
                 valid=None, log_proposal=None):
        self.m = m                  # i32[B]
        self.theta = theta          # f32[B, D]
        self.distance = distance    # f32[B]
        self.accepted = accepted    # bool[B]
        self.log_weight = log_weight  # f32[B]
        self.stats = stats          # f32[B, S] flattened sum-stats
        self.valid = valid if valid is not None else accepted
        #: log density of the proposal that generated each candidate
        #: (reference ``transition_pd_prev``, smc.py:1024-1032) — the prior
        #: at t=0, the model-mix × KDE density at t>0
        self.log_proposal = (log_proposal if log_proposal is not None
                             else jnp.zeros_like(self.log_weight))

    def tree_flatten(self):
        return ((self.m, self.theta, self.distance, self.accepted,
                 self.log_weight, self.stats, self.valid,
                 self.log_proposal), None)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


import jax.tree_util as _tree_util  # noqa: E402

_tree_util.register_pytree_node_class(RoundResult)


class SamplingError(Exception):
    pass


def coarse_bucket(n: int, minimum: int = 4096) -> int:
    """Smallest power of SIXTEEN >= n (>= minimum) — the record-path
    shape quantization.  Exact row counts would bill a fresh ~2-4 s
    remote compile of every shape-keyed program per generation, and
    record counts GROW across a run as the acceptance rate falls, so
    even power-of-four buckets crossed a boundary mid-run (measured on
    the petab row).  Pow16 means at most 2-3 shapes ever; the <=16x
    NaN padding is cheap because record consumers reduce over
    NaN-aware / compressed-support paths."""
    return max(int(16 ** np.ceil(np.log2(max(n, 1)) / 4)), minimum)


def fetch_to_host(tree):
    """Materialize a (possibly global) device pytree as host numpy.

    Single-process arrays go through one bulk ``jax.device_get``.  Under
    ``jax.distributed`` a sharded round/loop output spans devices this
    process cannot address; there the global value is assembled with an
    allgather collective — every process calls this at the same point
    (SPMD control flow), so the collective is well-ordered.  Replicated
    global arrays (counters, scalars) read the local replica without any
    collective.

    Every call is charged to the process-global wire ledger
    (wire/transfer.py) so wire-byte regressions are machine-visible in
    the bench JSON.  The producing computation is synced BEFORE the
    transfer timer starts and its wait booked to ``compute_s``, so the
    recorded ``d2h_s``/``fetch_s`` are pure transfer (VERDICT r5 #3:
    without the sync, a cpu8 row booked 22.2 s of device compute as
    "transfer" for 0.133 MB moved).  Caveat: through the axon relay
    ``block_until_ready`` may return before remote execution finishes,
    so on that backend a residue of compute can still land in fetch
    time; on local backends the split is exact.
    """
    import time as _time

    import jax

    from ..resilience import faults as _faults
    from ..resilience import retry as _retry
    from ..telemetry import spans
    from ..wire import transfer

    t0 = _time.perf_counter()
    with spans.span("wire.sync"):
        try:
            jax.block_until_ready(tree)
        except Exception:
            pass  # non-array leaves / exotic backends: split advisory
    transfer.record_compute(_time.perf_counter() - t0)

    def get(leaf):
        if getattr(leaf, "is_fully_addressable", True):
            return leaf  # bulk-fetched below
        if getattr(leaf, "is_fully_replicated", False):
            return np.asarray(leaf.addressable_shards[0].data)
        # cross-host assembly: the classic per-generation path's only
        # global sync point.  Pod one-dispatch runs never reach here in
        # steady state (summary lanes are replicated, wires drain
        # shard-local); setup/teardown and eager multi-host fetches do,
        # and the seconds land on the ledger's ``collective_s`` so the
        # zero-steady-state-sync contract is machine-checkable.
        from jax.experimental import multihost_utils
        c0 = _time.perf_counter()
        out = np.asarray(multihost_utils.process_allgather(  # collective-ok: d2h chokepoint, SPMD-ordered
            leaf, tiled=True))
        transfer.record_collective(_time.perf_counter() - c0)
        return out
    import jax.tree_util as tu

    def _fetch():
        # one attempt: transfer + ledger commit; failed attempts charge
        # nothing to the byte counters (commit only runs on success)
        with spans.span("wire.fetch") as sp, transfer.timed_d2h() as timer:
            out = jax.device_get(tu.tree_map(get, tree))
        out = timer.commit(out)
        sp.set(nbytes=transfer._tree_nbytes(out))
        return out

    # the d2h retry chokepoint: transient link failures (relay drops,
    # preempted remote runtimes) back off and re-pull through the SAME
    # path on every caller — sampler loops and background ingest
    # workers alike (tools/check_retry_sites.py)
    return _retry.shared_policy().call(_fetch, _faults.SITE_FETCH)


def fetch_local_shard(tree):
    """This process's contiguous rows of a (possibly global) device
    pytree — NO cross-host traffic, ever.

    The pod drain/durability contract (docs/performance.md "Pod
    scale"): on the host-major pod mesh each process's addressable
    shards of a P("particles") array are one contiguous row range, so
    concatenating them in shard order yields exactly this host's slice
    of the global value.  Replicated leaves (scales, counters) return
    the full local replica; single-process arrays are equivalent to
    ``fetch_to_host``.  Used by the per-host journal spill
    (wire/store.py) and the preemption barrier, where a collective
    would hang on already-dying peers.
    """
    import time as _time

    import jax

    from ..wire import transfer

    t0 = _time.perf_counter()
    try:
        jax.block_until_ready(tree)
    except Exception:
        pass
    transfer.record_compute(_time.perf_counter() - t0)

    def get(leaf):
        if getattr(leaf, "is_fully_addressable", True) \
                or getattr(leaf, "is_fully_replicated", False):
            shards = getattr(leaf, "addressable_shards", None)
            if shards is not None and not leaf.is_fully_addressable:
                return np.asarray(shards[0].data)
            return np.asarray(leaf)
        shards = sorted(leaf.addressable_shards,
                        key=lambda s: s.index[0].start or 0)
        return np.concatenate([np.asarray(s.data) for s in shards],
                              axis=0)
    import jax.tree_util as tu

    # booked under the CALLER's egress label (journal spills wrap this
    # in egress("history"); checkpoints in egress("checkpoint"))
    with transfer.timed_d2h() as timer:
        out = tu.tree_map(get, tree)
    return timer.commit(out)


def widen_wire(out: dict, take: int) -> dict:
    """THE wire decoder (host side of ``device_loop.narrow_wire``):
    bit-unpack the model column, multiply the per-column power-of-two
    scales back in, widen f16 to f32, truncate to ``take`` rows.
    Returns numpy ``m``/``theta``/``distance``/``log_weight``
    (/``stats`` when it rode the wire).  Charged to the wire ledger's
    ``decode_s`` — decode is the third stage of the ingest path next to
    ``compute_s``/``fetch_s``."""
    import time as _time

    from ..telemetry import spans
    from ..wire import transfer

    t0 = _time.perf_counter()
    with spans.span("wire.decode", rows=int(take)):
        if "m_bits" in out:
            # unpackbits may carry up to 7 zero-pad tail bits
            m = np.unpackbits(np.asarray(out["m_bits"]))[:take]
        else:
            m = np.asarray(out["m"][:take])
        batch = {"m": m.astype(np.int32)}
        for k in ("theta", "distance", "log_weight", "stats"):
            if k not in out:
                continue
            v = np.asarray(out[k][:take], dtype=np.float32)
            scale = out.get(f"{k}_scale")  # per-column [d] or scalar
            batch[k] = (v * np.asarray(scale, dtype=np.float32)
                        if scale is not None else v)
    transfer.record_decode(_time.perf_counter() - t0)
    return batch


_NAN_MASK_CACHE: dict = {}


def _nan_mask_records(batch: dict, rc) -> dict:
    """NaN out record rows at index >= rc (device op; the jitted masker is
    module-cached so it compiles once per bucket shape — rc is a traced
    argument, not a shape)."""
    if "fn" not in _NAN_MASK_CACHE:
        from ..autotune import jit_compile

        @jit_compile
        def mask(batch, rc):
            keep = jnp.arange(batch["distance"].shape[0]) < rc
            out = {}
            for k in ("stats", "distance", "theta", "log_proposal"):
                v = batch[k]
                m = keep[:, None] if v.ndim == 2 else keep
                out[k] = jnp.where(m, v, jnp.nan)
            out["accepted"] = batch["accepted"] & keep
            out["m"] = jnp.where(keep, batch["m"], 0)
            return out

        _NAN_MASK_CACHE["fn"] = mask
    return _NAN_MASK_CACHE["fn"](batch, rc)


class Sample:
    """Host-side accumulator over rounds (parity: sampler/base.py:17-120).

    ``record_rejected`` mirrors ``SampleFactory.record_rejected``
    (sampler/base.py:60-77): when set (by adaptive distances / temperature
    schemes via configure_sampler), ALL candidate sum-stats are kept up to
    ``max_records`` so per-generation adaptation can see rejected particles.
    """

    #: pod opt-in (set by the orchestrator when the run is in pod
    #: one-dispatch posture): keep ``device_population`` even when its
    #: leaves span processes.  Under SPMD every process holds the same
    #: GLOBAL view, all device consumers (carry seeding, on-device
    #: refits, summary packets) are jit programs over the global mesh,
    #: and the only host materializations are replicated reductions or
    #: annotated setup/teardown fetches — so the single-process
    #: addressability requirement is exactly what pod runs relax.
    #: Default False: the classic multi-host path stays byte-identical.
    allow_global_device_view = False

    def __init__(self, record_rejected: bool = False,
                 max_records: int = 1 << 21):
        self.record_rejected = record_rejected
        self.max_records = max_records
        self._acc: List[dict] = []
        self._rec: List[dict] = []
        self._n_recorded = 0
        self.nr_evaluations = 0
        #: ALL acceptances observed, incl. over-provisioned beyond the
        #: requested n (for unbiased acceptance-rate accounting)
        self.raw_accepted = 0
        #: optional host callback set by the orchestrator before
        #: ``eps.update``: ``(m[R], theta[R, D]) -> log-density`` of the
        #: NEWLY fitted proposal (reference ``transition_pd``,
        #: smc.py:1022-1032); None -> importance ratio 1
        self.transition_log_pdf = None
        #: optional DEVICE density callback set by the orchestrator:
        #: ``(m_dev[R], theta_dev[R, D]) -> log-density`` of the newly
        #: fitted proposal, evaluated without leaving the device —
        #: enables `get_records_device` (temperature schemes solve on
        #: device instead of fetching ~MBs of record columns)
        self.transition_log_pdf_device = None
        #: device-resident view of the accepted buffers (m/theta/
        #: log_weight/count), set by append_device_batch when available
        self.device_population: Optional[dict] = None
        #: device-resident NARROW wire payload whose big fetch was
        #: deferred (``defer_wire_fetch``) so a streaming-ingest engine
        #: can overlap it with the next generation's compute (wire/)
        self.pending_wire: Optional[dict] = None
        self._pending_count = 0

    def append_round(self, rr: RoundResult):
        rr = fetch_to_host(rr)
        acc_mask = np.asarray(rr.accepted)
        self.nr_evaluations += int(acc_mask.shape[0])
        self.raw_accepted += int(acc_mask.sum())
        idx = np.nonzero(acc_mask)[0]
        if idx.size:
            self._acc.append({
                "m": np.asarray(rr.m)[idx],
                # pop-ok: round-batch rows (B, not pop), already
                # through the wire chokepoint via fetch_to_host
                "theta": np.asarray(rr.theta)[idx],  # pop-ok
                "distance": np.asarray(rr.distance)[idx],
                "log_weight": np.asarray(rr.log_weight)[idx],  # pop-ok
                "stats": np.asarray(rr.stats)[idx],
            })
        if self.record_rejected and self._n_recorded < self.max_records:
            valid = np.nonzero(np.asarray(rr.valid))[0]
            take = valid[: self.max_records - self._n_recorded]
            self._rec.append({
                "stats": np.asarray(rr.stats)[take],
                "distance": np.asarray(rr.distance)[take],
                "accepted": acc_mask[take],
                "m": np.asarray(rr.m)[take],
                "theta": np.asarray(rr.theta)[take],  # pop-ok: B rows
                "log_proposal": np.asarray(rr.log_proposal)[take],
                "__count": int(take.size),
            })
            self._n_recorded += take.size

    def append_device_batch(self, out: dict, n_evals: int,
                            device_view: Optional[dict] = None):
        """Ingest one on-device generation batch (sampler/device_loop.py):
        a single host transfer of the compacted accepted buffers (+ records).

        ``out`` is the WIRE payload — already host-fetched by the caller,
        with the float columns max-normalized and narrowed to f16 (and
        possibly no ``stats`` block at all); this method multiplies the
        power-of-two scales back in and widens to f32.

        ``device_view`` carries the same batch's un-fetched f32 device
        arrays; they are kept on :attr:`device_population` so the
        orchestrator can build the next generation's transition support
        ON device (smc.py `_device_supports`) instead of re-uploading
        ~MBs of host-padded support through the relay.
        """
        if device_view is not None and (
                self.allow_global_device_view
                or all(getattr(v, "is_fully_addressable", True)
                       for v in device_view.values())):
            self.device_population = {
                k: device_view[k]
                for k in ("m", "theta", "log_weight", "stats",
                          "distance")}
            self.device_population["count"] = device_view["count"]
        self.nr_evaluations += int(n_evals)
        count = int(out["count"])
        self.raw_accepted += count
        take = min(count, out["theta"].shape[0])
        if take:
            # stats may be deliberately missing from the wire (no host
            # consumer exists — adaptive distances force fetch_stats=True
            # upstream, and device consumers read device_population);
            # attaching a device slice here would bill a fresh
            # exact-shape kernel every generation for data nobody reads
            self._acc.append(widen_wire(out, take))
        if self.record_rejected and "rec_count" in out:
            rc = min(int(out["rec_count"]),
                     self.max_records - self._n_recorded)
            if rc > 0:
                self._rec.append({
                    "stats": np.asarray(out["rec_stats"][:rc]),
                    "distance": np.asarray(out["rec_distance"][:rc]),
                    "accepted": np.asarray(out["rec_accepted"][:rc]),
                    "m": np.asarray(out["rec_m"][:rc]),
                    # pop-ok: record-ring rows (max_records cap)
                    "theta": np.asarray(out["rec_theta"][:rc]),  # pop-ok
                    "log_proposal": np.asarray(
                        out["rec_log_proposal"][:rc]),
                    "__count": rc,
                })
                self._n_recorded += rc

    def append_pending_wire(self, wire_dev: dict, n_evals: int,
                            count: int,
                            device_view: Optional[dict] = None):
        """Defer the big accepted-buffer fetch: keep the narrow wire
        payload device-resident so the orchestrator can hand it to a
        :class:`~pyabc_tpu.wire.streaming.StreamingIngest` engine and
        overlap the d2h transfer with the next generation's compute.

        ``count`` was already synced as a cheap scalar by the sampler;
        evaluation/acceptance accounting is identical to
        ``append_device_batch`` so undershoot checks and rate estimates
        see the same numbers whether or not the fetch ran yet.
        """
        if device_view is not None and (
                self.allow_global_device_view
                or all(getattr(v, "is_fully_addressable", True)
                       for v in device_view.values())):
            self.device_population = {
                k: device_view[k]
                for k in ("m", "theta", "log_weight", "stats",
                          "distance")}
            self.device_population["count"] = device_view["count"]
        self.nr_evaluations += int(n_evals)
        self.raw_accepted += int(count)
        self.pending_wire = wire_dev
        self._pending_count = int(count)

    def take_pending_wire(self) -> Optional[dict]:
        """Hand ownership of the deferred wire to an ingest engine.  The
        accepted-count accounting stays in place — the rows exist, just
        not host-side — so ``n_accepted`` keeps reporting them."""
        wire_dev, self.pending_wire = self.pending_wire, None
        return wire_dev

    def resolve_pending(self):
        """Fetch + ingest a deferred wire inline — the safety net for
        consumers that need host rows when no ingest engine took the
        wire (``get_accepted_population`` calls this first)."""
        if self.pending_wire is None:
            return
        wire_dev = self.take_pending_wire()
        out = fetch_to_host(wire_dev)
        count, self._pending_count = self._pending_count, 0
        take = min(count, out["theta"].shape[0])
        if take:
            self._acc.append(widen_wire(out, take))

    def splice_front(self, batch: dict, nr_evaluations: int):
        """Prepend rows restored from a mid-generation sub-checkpoint
        (resilience/checkpoint.py): the preempted process flushed them
        in round order BEFORE any row of this sample was drawn, so
        front insertion preserves the deterministic round-order
        truncation contract.  Evaluation counts add exactly (the
        flushed rounds ran once, in the killed process; this process
        never re-ran them), and the raw log-weights normalize together
        in :meth:`get_accepted_population` — both halves are draws from
        the same proposal at the same eps, so the spliced population is
        statistically identical to an uninterrupted one."""
        self.resolve_pending()
        self._acc.insert(0, batch)
        self.nr_evaluations += int(nr_evaluations)
        self.raw_accepted += int(batch["m"].shape[0])
        # the device-resident view covers only this process's rows —
        # it no longer represents the whole generation, so device
        # consumers (fused carry, device transition fits) must rebuild
        # from the host population
        self.device_population = None

    def append_record_batch(self, rec: dict):
        """Ingest one per-call record harvest (``rec_*`` buffers + count)
        from the stateful device loop; capped at ``max_records`` across
        calls with earliest-first retention, like the reference's
        first-m-particles accounting (smc.py:1009-1010).

        The arrays stay DEVICE-resident (no transfer here): the heaviest
        consumer — the adaptive distance's scale refit over ``stats``
        ``[R, S]`` — is itself a device reduction, so fetching the block
        to host only to push it back cost ~50 % of an adaptive-distance
        generation through the relay.  Host consumers (temperature
        schemes) materialize just the columns they need.
        """
        if not self.record_rejected:
            return
        # callers that already synced rec_count pass it in, avoiding a
        # second blocking scalar transfer through the relay
        rec_count = rec.get("rec_count_host")
        if rec_count is None:
            rec_count = int(rec["rec_count"])
        rc = min(int(rec_count), self.max_records - self._n_recorded)
        if rc <= 0:
            return
        # slice device arrays at a COARSE bucket, not the exact count: an
        # exact dynamic length would compile a fresh slice kernel every
        # generation (~4 s/gen through the remote compiler); the bucketed
        # shapes are few and cache.  Rows >= rc are then NaN-masked with
        # the count as a traced ARGUMENT (cached per bucket shape), so the
        # tail is exactly NaN even when the max_records budget truncated
        # below the harvested count.  NaN-aware reducers (the scale fns)
        # consume the buffers directly; exact-count consumers use the
        # stored "__count" after host materialization.
        cap = rec["rec_stats"].shape[0]
        bucket = min(coarse_bucket(rc), cap)
        batch = _nan_mask_records(
            {k: rec[f"rec_{k}"][:bucket]
             for k in ("stats", "distance", "accepted", "m", "theta",
                       "log_proposal")}, rc)
        density_fn = rec.get("record_density_fn")
        if density_fn is not None:
            # rounds ran in deferred mode (no per-candidate KDE); give the
            # RECORDS real generating-proposal densities over the bucketed
            # slice — total density work is bounded by the record budget,
            # not rounds x batch.  NaN-masked tail rows yield NaN, as the
            # record contract expects.
            batch["log_proposal"] = density_fn(batch["m"], batch["theta"])
        batch["__count"] = rc
        self._rec.append(batch)
        self._n_recorded += rc

    @property
    def n_accepted(self) -> int:
        """Accepted rows — host-ingested plus any still riding a
        deferred (or engine-taken) wire."""
        return (sum(a["m"].shape[0] for a in self._acc)
                + self._pending_count)

    @property
    def acceptance_rate(self) -> float:
        """Unbiased: raw acceptances (incl. beyond-n) / evaluations."""
        return self.raw_accepted / max(self.nr_evaluations, 1)

    def _concat(self, dicts: List[dict], key: str):
        """Concatenate batches of one column; device batches (record
        stats) concatenate ON device — np.concatenate would silently pull
        every batch through the relay."""
        arrs = [d[key] for d in dicts]
        if any(not isinstance(a, np.ndarray) for a in arrs):
            import jax.numpy as jnp
            return jnp.concatenate(arrs, axis=0)
        return np.concatenate(arrs, axis=0)

    def get_accepted_population(self, n: int) -> Population:
        """First n accepted particles in deterministic round order."""
        self.resolve_pending()
        host_rows = sum(a["m"].shape[0] for a in self._acc)
        if host_rows < n:
            raise SamplingError(
                f"expected {n} accepted particles, have {host_rows} "
                "(contract check, cf. reference sampler/base.py:154-157)")
        m = self._concat(self._acc, "m")[:n]
        theta = self._concat(self._acc, "theta")[:n]
        dist = self._concat(self._acc, "distance")[:n]
        logw = self._concat(self._acc, "log_weight")[:n]
        # stats may be absent entirely (no-host-consumer wire mode under
        # a multi-host mesh, where no addressable device view exists)
        stats = (self._concat(self._acc, "stats")[:n]
                 if all("stats" in a for a in self._acc) else None)
        # normalize in log space for f32 safety; arrays stay numpy — the
        # population is control-plane state (fits, quantiles, DB writes)
        # and must not cost device dispatches
        logw = logw - logw.max() if logw.size else logw
        w = np.exp(np.asarray(logw, dtype=np.float64))
        s = w.sum()
        if not np.isfinite(s) or s <= 0:
            raise SamplingError("all accepted particles have zero weight")
        return Population(
            m=m, theta=theta,
            weight=(w / s).astype(np.float32), distance=dist,
            sum_stats={"__flat__": stats} if stats is not None else {},
        )

    def get_all_stats(self) -> np.ndarray:
        """All recorded candidate stats ``[R, S]`` (incl. rejected)."""
        if not self._rec:
            if self._acc and all("stats" in a for a in self._acc):
                return self._concat(self._acc, "stats")
            return np.zeros((0, 0), np.float32)
        return self._concat(self._rec, "stats")

    _RECORD_KEYS = ("m", "theta", "stats", "distance", "accepted",
                    "log_proposal")

    def get_records_arrays(self, keys=None) -> Optional[dict]:
        """Recorded candidates as EXACT-count numpy column arrays, or None
        if none.  Device batches are stored at coarse-bucket sizes with NaN
        tails (see append_record_batch); each requested column is
        materialized to host and truncated to the batch's true count.
        Pass ``keys`` to fetch only what you need — ``stats`` is the big
        [R, S] block and costs a relay transfer per batch."""
        if not self._rec:
            return None
        keys = tuple(keys if keys is not None else self._RECORD_KEYS)
        # ONE bundled host transfer for all requested columns of all
        # batches (per-column np.asarray would pay the relay's
        # per-transaction constant keys x batches times)
        from ..wire.transfer import egress

        with egress("summary"):
            fetched = fetch_to_host([{k: b[k] for k in keys}
                                     for b in self._rec])
        out = {}
        for k in keys:
            parts = [np.asarray(f[k])[:b["__count"]]
                     for f, b in zip(fetched, self._rec)]
            out[k] = np.concatenate(parts, axis=0)
        return out

    def get_records_columns(self) -> Optional[Dict[str, np.ndarray]]:
        """Per-candidate record columns for temperature schemes (reference
        smc.py:1008-1035): ``distance`` (acceptance-kernel value),
        ``transition_pd_prev`` (density of the proposal that generated the
        candidate, recorded at round time), ``transition_pd`` (density under
        the newly fitted proposal, via the orchestrator-set
        :attr:`transition_log_pdf` callback) and ``accepted``.  Densities
        are shifted by a common constant before exponentiation — schemes
        only use the ratio pd/pd_prev, which is shift-invariant.  Array
        columns (not dicts): at the 1e6-records scale the control plane
        must stay vectorized."""
        # the temperature schemes never read the [R, S] stats block —
        # don't pull it through the relay
        recs = self.get_records_arrays(
            keys=("m", "theta", "distance", "accepted", "log_proposal"))
        if recs is None:
            return None
        log_prev = np.asarray(recs["log_proposal"], dtype=np.float64)
        if self.transition_log_pdf is None:
            log_new = log_prev
        else:
            log_new = np.asarray(
                self.transition_log_pdf(recs["m"], recs["theta"]),
                dtype=np.float64)
        finite = np.concatenate([log_prev[np.isfinite(log_prev)],
                                 log_new[np.isfinite(log_new)]])
        shift = finite.max() if finite.size else 0.0
        return {
            "distance": np.asarray(recs["distance"], dtype=np.float64),
            "transition_pd_prev": np.exp(log_prev - shift),
            "transition_pd": np.exp(log_new - shift),
            "accepted": np.asarray(recs["accepted"], dtype=bool),
        }

    def get_records_device(self) -> Optional[dict]:
        """Device-resident record columns for temperature schemes:
        ``log_dens`` (the recorded kernel value) and ``log_ratio``
        (log new-proposal density − log generating-proposal density,
        via :attr:`transition_log_pdf_device`) — NaN rows are bucket
        padding / truncated tails and must be masked by the consumer.

        Returns None when the device fast path is unavailable (host
        record batches, or no device density callback); callers fall
        back to :meth:`get_records_columns`.  Fetches NOTHING: the
        whole point is that an on-device temperature solve replaces
        ~MBs of per-candidate column fetch + re-upload per generation
        (measured ~2.2 s/gen on the petab row through the relay).
        """
        if not self._rec or self.transition_log_pdf_device is None:
            return None
        if any(isinstance(b["distance"], np.ndarray) for b in self._rec):
            return None
        dist = self._concat(self._rec, "distance")
        log_prev = self._concat(self._rec, "log_proposal")
        m = self._concat(self._rec, "m")
        theta = self._concat(self._rec, "theta")
        log_new = self.transition_log_pdf_device(m, theta)
        return {"log_dens": dist, "log_ratio": log_new - log_prev}

    def get_all_records(self) -> List[dict]:
        """Reference-compat list-of-dicts view of
        :meth:`get_records_columns` (reference smc.py:726-737).

        COMPAT PATH: building one Python dict per record is O(R) host
        work — at the 1e6-record scale this stalls for seconds where the
        column view is instant.  Nothing in this package calls it; a
        consumer that does gets a loud warning pointing at
        :meth:`get_records_columns`."""
        cols = self.get_records_columns()
        if cols is None:
            return []
        n = cols["distance"].shape[0]
        if n > 100_000:
            import warnings
            warnings.warn(
                f"Sample.get_all_records materializes {n} per-record "
                "dicts (O(R) Python); use get_records_columns() for "
                "vectorized access at this scale", RuntimeWarning,
                stacklevel=2)
        return [{k: v[i].item() for k, v in cols.items()}
                for i in range(n)]


class Sampler:
    """Abstract sampler (parity: pyabc/sampler/base.py:171-233)."""

    import itertools as _itertools
    _uid_counter = _itertools.count()

    def __init__(self):
        #: stable identity for compiled-program caches that bake in
        #: sampler state (mesh, axis) — id() of a freed sampler can be
        #: reused and would serve stale compiled closures
        self._uid = next(Sampler._uid_counter)
        self.nr_evaluations_ = 0
        self.record_rejected = False
        #: whether the [n, s] sum-stats block must ride the d2h wire; the
        #: orchestrator clears it when NO host consumer exists (History
        #: with stores_sum_stats=False and a non-adaptive distance) — at
        #: the 1e6 north star the block is ~a quarter of the whole
        #: generation's relay budget
        self.fetch_stats = True
        #: set (with record_rejected) by TemperatureBase.configure_sampler:
        #: records must carry real per-candidate proposal densities.
        #: Rounds still skip the KDE (deferred mode); the densities are
        #: computed over the BUCKETED record slices at ingest
        #: (Sample.append_record_batch), bounded by the record budget
        self.record_proposal_density = False
        self.show_progress = False
        #: cap on recorded candidates per generation; the orchestrator sets
        #: this from ABCSMC.max_nr_recorded_particles (reference
        #: smc.py:1009-1010 first_m_particles)
        self.max_records = 1 << 21
        self.sample_factory = self  # reference-compat alias
        #: bounded-backoff retry policy every device dispatch routes
        #: through (:meth:`_dispatch`; resilience/retry.py)
        from ..resilience.retry import RetryPolicy
        self._retry = RetryPolicy.from_env()
        #: mid-generation sub-checkpoint sink, set by the sequential
        #: run path for the duration of one generation
        #: (resilience/checkpoint.py GenCheckpointer); None = disabled
        self.checkpointer = None

    def _dispatch(self, fn, *args):
        """THE device-dispatch chokepoint: every compiled-program call
        in a sampler loop goes through here so transient backend
        failures retry with backoff and injected faults have one
        deterministic site (``device.dispatch``).  Enforced by the
        tools/check_retry_sites.py lint, like check_wire_chokepoint.py
        enforces the d2h chokepoint."""
        from ..resilience.faults import SITE_DISPATCH
        return self._retry.call(fn, SITE_DISPATCH, *args)

    def sample_until_n_accepted(
            self, n: int,
            round_fn: Callable,
            key,
            params,
            max_eval: float = np.inf,
            all_accepted: bool = False,
            **kwargs) -> Sample:
        raise NotImplementedError

    def stop(self):
        """Teardown hook (reference redis sampler parity)."""
