"""Lotka-Volterra stochastic predator-prey model (BASELINE config #3).

TPU design: Euler-Maruyama SDE integration under ``lax.scan`` with the
whole particle batch advanced per step — the time loop is sequential but
every step is a [N, 2] vectorized update, so N=1e5+ particles integrate in
lockstep on the MXU/VPU.  Summary statistics are reductions over the stored
trajectory.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
from jax import lax

from ..distance import AdaptivePNormDistance
from ..model import Model
from ..random_variables import RV, Distribution

Array = jnp.ndarray


class LotkaVolterraSDE(Model):
    """dX = (a·X − b·X·Y)dt + σ√X dW₁ ; dY = (c·b·X·Y − d·Y)dt + σ√Y dW₂.

    Parameters theta = [log_a, log_b, log_c, log_d] (log scale keeps the
    prior unbounded while rates stay positive).
    """

    #: the low-fidelity variant keeps the exact summary-stat layout
    #: (fidelity-cascade contract, docs/fidelity.md)
    screen_stats_compatible = True

    def __init__(self, x0: float = 10.0, y0: float = 5.0,
                 t_max: float = 15.0, n_steps: int = 300,
                 sigma: float = 0.1, n_obs: int = 10,
                 name: str = "lotka_volterra_sde"):
        super().__init__(name)
        self.x0, self.y0 = float(x0), float(y0)
        self.t_max, self.n_steps = float(t_max), int(n_steps)
        self.dt = self.t_max / self.n_steps
        self.sigma = float(sigma)
        self.n_obs = int(n_obs)
        # observation indices: n_obs equally spaced time points
        self.obs_idx = jnp.linspace(0, n_steps - 1, n_obs).astype(jnp.int32)

    def sample(self, key, theta: Array) -> Dict[str, Array]:
        n = theta.shape[0]
        a, b, c, d = (jnp.exp(theta[:, i]) for i in range(4))
        dt, sig = self.dt, self.sigma
        sqrt_dt = jnp.sqrt(dt)

        def step(state, noise):
            x, y = state
            dx = (a * x - b * x * y) * dt + sig * jnp.sqrt(
                jnp.maximum(x, 0.0)) * sqrt_dt * noise[:, 0]
            dy = (c * b * x * y - d * y) * dt + sig * jnp.sqrt(
                jnp.maximum(y, 0.0)) * sqrt_dt * noise[:, 1]
            x = jnp.maximum(x + dx, 0.0)
            y = jnp.maximum(y + dy, 0.0)
            return (x, y), jnp.stack([x, y], axis=-1)

        noises = jax.random.normal(key, (self.n_steps, n, 2))
        init = (jnp.full((n,), self.x0), jnp.full((n,), self.y0))
        _, traj = lax.scan(step, init, noises)   # [T, N, 2]
        obs = traj[self.obs_idx]                 # [n_obs, N, 2]
        return {
            "prey": jnp.moveaxis(obs[..., 0], 0, -1),      # [N, n_obs]
            "predator": jnp.moveaxis(obs[..., 1], 0, -1),  # [N, n_obs]
        }

    def low_fidelity(self) -> "LotkaVolterraSDE":
        """4x coarser Euler-Maruyama grid over the same horizon and
        observation points — the oscillation phase/amplitude stays
        correlated with the full integration, which is all the
        screening calibrator requires."""
        coarse = max(self.n_steps // 4, self.n_obs, 1)
        return LotkaVolterraSDE(x0=self.x0, y0=self.y0, t_max=self.t_max,
                                n_steps=coarse, sigma=self.sigma,
                                n_obs=self.n_obs,
                                name=self.name + "_lofi")


def make_lotka_volterra_problem(key=None):
    """(models, priors, distance, observed) with synthetic ground truth."""
    model = LotkaVolterraSDE()
    prior = Distribution(
        log_a=RV("uniform", -1.0, 2.0),
        log_b=RV("uniform", -3.0, 2.0),
        log_c=RV("uniform", -2.0, 2.0),
        log_d=RV("uniform", -1.0, 2.0),
    )
    if key is None:
        key = jax.random.PRNGKey(7)
    # ground-truth params: a=1.1, b=0.4, c=1.0 (scaling of b), d=0.4
    theta_true = jnp.log(jnp.asarray([[1.1, 0.4, 1.0, 0.4]]))
    obs = model.simulate(key, theta_true)
    observed = {k: v[0] for k, v in obs.items()}
    return [model], [prior], AdaptivePNormDistance(p=2), observed
