"""PEtab problem-directory ingestion: YAML + tables + SBML -> runnable model.

Reference parity: ``AmiciPetabImporter`` (pyabc/petab/amici.py:26-170)
takes a ``petab.Problem`` and produces model/prior/kernel with zero user
code — the SBML model is compiled by AMICI (:72-116) and simulations
return the measurement log-likelihood as the single summary statistic.

Here the same contract is met TPU-natively: the SBML subset parser
(petab/sbml.py) builds a batched JAX RHS, the whole population integrates
in one fixed-step RK4 ``lax.scan``, observables are evaluated from the
trajectory via the PEtab observable formulas, and the measurement
log-likelihood (normal/laplace noise, lin/log/log10 transformations) is a
fused reduction.  ``ODEPetabImporter`` (petab/ode.py) remains the manual
escape hatch for models outside the SBML subset.
"""

from __future__ import annotations

import os
from typing import Dict, List

import jax.numpy as jnp
import numpy as np

from ..distance.kernel import SCALE_LOG, SimpleFunctionKernel
from ..model import Model
from .base import LIN, LOG, LOG10, PetabImporter
from .ode import LLH
from .sbml import ExprError, SBMLModel, eval_expr, parse_sbml

Array = jnp.ndarray


def _read_table(path: str):
    import pandas as pd
    sep = "\t" if path.endswith((".tsv", ".txt")) else ","
    return pd.read_csv(path, sep=sep)


class PetabProblem:
    """A loaded PEtab problem: tables + parsed SBML model.

    ``from_yaml`` reads the standard PEtab YAML layout; the constructor
    also accepts in-memory DataFrames + an :class:`SBMLModel` (or SBML
    XML string) for programmatic use.
    """

    def __init__(self, sbml_model, parameter_df, observable_df,
                 measurement_df, condition_df=None):
        if isinstance(sbml_model, str):
            sbml_model = parse_sbml(sbml_model)
        self.model: SBMLModel = sbml_model
        self.parameter_df = parameter_df.set_index("parameterId") \
            if "parameterId" in parameter_df.columns else parameter_df
        self.observable_df = observable_df.set_index("observableId") \
            if "observableId" in observable_df.columns else observable_df
        self.measurement_df = measurement_df
        self.condition_df = condition_df
        if condition_df is not None and "conditionId" in condition_df.columns:
            self.condition_df = condition_df.set_index("conditionId")

    @classmethod
    def from_yaml(cls, path: str) -> "PetabProblem":
        import yaml
        with open(path) as f:
            spec = yaml.safe_load(f)
        base = os.path.dirname(os.path.abspath(path))

        def resolve(name):
            return os.path.join(base, name)

        import pandas as pd
        prob = spec["problems"][0]
        parameter_file = spec.get("parameter_file") or prob.get(
            "parameter_file")
        parameter_df = _read_table(resolve(parameter_file))
        sbml_files = prob.get("sbml_files") or [prob["sbml_file"]]
        sbml_model = parse_sbml(resolve(sbml_files[0]))
        observable_df = pd.concat(
            [_read_table(resolve(f)) for f in prob["observable_files"]])
        measurement_df = pd.concat(
            [_read_table(resolve(f)) for f in prob["measurement_files"]])
        condition_df = None
        if prob.get("condition_files"):
            condition_df = pd.concat(
                [_read_table(resolve(f)) for f in prob["condition_files"]])
        return cls(sbml_model, parameter_df, observable_df, measurement_df,
                   condition_df)

    def estimated_ids(self) -> List[str]:
        df = self.parameter_df
        est = df[df.get("estimate", 1).astype(int) == 1] \
            if "estimate" in df.columns else df
        return [str(i) for i in est.index]

    def parameter_scales(self) -> Dict[str, str]:
        df = self.parameter_df
        if "parameterScale" not in df.columns:
            return {str(i): LIN for i in df.index}
        return {str(i): str(s) for i, s in df["parameterScale"].items()}

    def nominal_values(self) -> Dict[str, float]:
        df = self.parameter_df
        if "nominalValue" not in df.columns:
            return {}
        return {str(i): float(v) for i, v in df["nominalValue"].items()
                if np.isfinite(v)}


def _unscale(value, scale: str):
    if scale == LOG:
        return jnp.exp(value)
    if scale == LOG10:
        return 10.0**value
    return value


class PetabSBMLModel(Model):
    """Batched RK4 simulation of a PEtab problem returning ``{'llh': [N]}``
    (reference amici.py:117-147: AMICI returns the problem llh per
    parameter vector; here the whole population integrates at once).

    One integration per simulation condition (conditions are few; the
    population axis is the batch).  Measurement times are read off the
    trajectory by linear interpolation, so arbitrary PEtab time points
    need no grid alignment.
    """

    def __init__(self, problem: PetabProblem, n_steps: int = 200,
                 name: str = "petab_sbml"):
        super().__init__(name)
        self.problem = problem
        self.n_steps = int(n_steps)
        self._rhs = problem.model.make_rhs()
        self._state_ids = problem.model.state_ids()
        self._scales = problem.parameter_scales()
        self._estimated = problem.estimated_ids()
        self._nominal = problem.nominal_values()
        self._conditions = self._group_measurements()
        self._t_max = max(
            (float(row["time"]) for _, _, rows in self._conditions
             for row in rows),
            default=1.0) or 1.0

    # ---- measurement bookkeeping ---------------------------------------

    def _group_measurements(self):
        """[(condition_id, overrides, rows)] with rows =
        [{observableId, time, measurement, noise_override}]."""
        mdf = self.problem.measurement_df
        groups = []
        cond_ids = (mdf["simulationConditionId"].unique()
                    if "simulationConditionId" in mdf.columns else [None])
        for cid in cond_ids:
            sel = mdf if cid is None else mdf[
                mdf["simulationConditionId"] == cid]
            overrides = {}
            if cid is not None and self.problem.condition_df is not None \
                    and cid in self.problem.condition_df.index:
                row = self.problem.condition_df.loc[cid]
                for col, val in row.items():
                    if col in ("conditionName",):
                        continue
                    if isinstance(val, float) and np.isnan(val):
                        continue
                    overrides[str(col)] = val
            rows = []
            for _, r in sel.iterrows():
                rows.append({
                    "observableId": str(r["observableId"]),
                    "time": float(r["time"]),
                    "measurement": float(r["measurement"]),
                    "noiseParameters": r.get("noiseParameters"),
                    "observableParameters": r.get("observableParameters"),
                })
            groups.append((cid, overrides, rows))
        return groups

    # ---- simulation -----------------------------------------------------

    def _theta_env(self, theta: Array) -> Dict[str, Array]:
        """Estimated parameters (unscaled, [N]) + fixed nominals.

        Only theta needs unscaling: estimated parameters travel on the
        objective (parameterScale) scale, while the table's nominalValue
        column is ALWAYS linear-scale per the PEtab spec."""
        env = {}
        for pid, val in self._nominal.items():
            if pid not in self._estimated:
                env[pid] = val
        for j, pid in enumerate(self._estimated):
            env[pid] = _unscale(theta[:, j], self._scales.get(pid, LIN))
        return env

    def _resolve_override(self, val, env, n):
        """A condition-table cell: numeric, or a parameter/entity name."""
        try:
            return jnp.full((n,), float(val))
        except (TypeError, ValueError):
            pass
        name = str(val)
        if name in env:
            return jnp.broadcast_to(env[name], (n,))
        base = self.problem.model.base_env()
        if name in base:
            return jnp.full((n,), float(base[name]))
        raise ExprError(f"cannot resolve condition override {val!r}")

    def _integrate(self, theta_env: Dict[str, Array],
                   overrides: Dict[str, object], n: int):
        """RK4 over the grid; returns (times [T+1], state [T+1, N, S])."""
        from jax import lax

        model = self.problem.model
        dt = self._t_max / self.n_steps
        y0_vals = model.y0()
        y0_cols = []
        for i, sid in enumerate(self._state_ids):
            if sid in overrides:
                y0_cols.append(self._resolve_override(
                    overrides[sid], theta_env, n))
            else:
                y0_cols.append(jnp.full((n,), y0_vals[i]))
        y = jnp.stack(y0_cols, axis=-1)
        env = dict(theta_env)
        for k, v in overrides.items():
            if k not in self._state_ids:
                env[k] = self._resolve_override(v, theta_env, n)

        def step(carry, i):
            y = carry
            t = i * dt
            k1 = self._rhs(y, env, t)
            k2 = self._rhs(y + 0.5 * dt * k1, env, t + 0.5 * dt)
            k3 = self._rhs(y + 0.5 * dt * k2, env, t + 0.5 * dt)
            k4 = self._rhs(y + dt * k3, env, t + dt)
            y = y + dt / 6.0 * (k1 + 2 * k2 + 2 * k3 + k4)
            return y, y

        _, traj = lax.scan(step, y, jnp.arange(self.n_steps))
        full = jnp.concatenate([y[None], traj], axis=0)   # [T+1, N, S]
        times = np.linspace(0.0, self._t_max, self.n_steps + 1)
        return times, full, env

    def _observable_series(self, obs_id: str, full, env, row=None):
        """Evaluate the observable formula over the trajectory -> [N, T+1].
        ``observableParameter{n}_{obsId}`` placeholders resolve from the
        measurement row's observableParameters column."""
        odf = self.problem.observable_df
        formula = str(odf.loc[obs_id, "observableFormula"])
        # [N]-shaped parameter arrays get a trailing axis so formulas can
        # mix them with [N, T+1] state series (e.g. 'scaling_par * A')
        local = {k: (v[:, None] if getattr(v, "ndim", 0) == 1 else v)
                 for k, v in env.items()}
        for i, sid in enumerate(self._state_ids):
            local[sid] = jnp.moveaxis(full[..., i], 0, -1)   # [N, T+1]
        base = self.problem.model.base_env()
        for k, v in base.items():
            local.setdefault(k, v)
        local = self.problem.model.resolve_assignments(local) \
            if self.problem.model.assignment_rules else local
        if row is not None:
            local.update(self._placeholder_env(
                "observableParameter", obs_id,
                row.get("observableParameters")))
        val = eval_expr(formula, local)
        n = full.shape[1]
        return jnp.broadcast_to(val, (n, full.shape[0]))

    @staticmethod
    def _placeholder_env(prefix: str, obs_id: str, cell) -> Dict[str, float]:
        if cell is None or (isinstance(cell, float) and np.isnan(cell)):
            return {}
        parts = str(cell).split(";")
        return {f"{prefix}{i + 1}_{obs_id}": float(p)
                for i, p in enumerate(parts)}

    def _noise_value(self, obs_id: str, env, row):
        odf = self.problem.observable_df
        formula = odf.loc[obs_id].get("noiseFormula", 1.0)
        if formula is None or (isinstance(formula, float)
                               and np.isnan(formula)):
            # a blank noiseFormula cell reads as NaN — default sigma,
            # like a missing column
            formula = 1.0
        local = dict(env)
        base = self.problem.model.base_env()
        for k, v in base.items():
            local.setdefault(k, v)
        local.update(self._placeholder_env(
            "noiseParameter", obs_id, row.get("noiseParameters")))
        return eval_expr(str(formula), local)

    def sample(self, key, theta: Array) -> Dict[str, Array]:
        n = theta.shape[0]
        env = self._theta_env(theta)
        llh = jnp.zeros((n,))
        odf = self.problem.observable_df
        for cid, overrides, rows in self._conditions:
            times, full, cenv = self._integrate(env, overrides, n)
            dt = times[1] - times[0] if len(times) > 1 else 1.0
            series_cache: Dict[str, Array] = {}
            for row in rows:
                oid = row["observableId"]
                has_op = row.get("observableParameters") is not None and \
                    not (isinstance(row.get("observableParameters"), float)
                         and np.isnan(row.get("observableParameters")))
                if oid in series_cache and not has_op:
                    series = series_cache[oid]
                else:
                    series = self._observable_series(
                        oid, full, cenv, row)
                    if not has_op:
                        series_cache[oid] = series
                # linear interpolation at the measurement time
                pos = row["time"] / dt
                i0 = int(np.clip(np.floor(pos), 0, len(times) - 2))
                frac = float(pos - i0)
                y_sim = series[:, i0] * (1 - frac) + series[:, i0 + 1] * frac
                sigma = self._noise_value(oid, cenv, row)
                sigma = jnp.broadcast_to(jnp.asarray(sigma, jnp.float32),
                                         (n,))
                m = row["measurement"]
                trans = LIN
                if "observableTransformation" in odf.columns:
                    tcell = odf.loc[oid, "observableTransformation"]
                    if isinstance(tcell, str):
                        trans = tcell
                dist = "normal"
                if "noiseDistribution" in odf.columns:
                    dcell = odf.loc[oid, "noiseDistribution"]
                    if isinstance(dcell, str):
                        dist = dcell
                if trans == LOG:
                    resid = jnp.log(m) - jnp.log(y_sim)
                    jac = -np.log(m)
                elif trans == LOG10:
                    resid = np.log10(m) - jnp.log10(y_sim)
                    jac = -np.log(m * np.log(10.0))
                else:
                    resid = m - y_sim
                    jac = 0.0
                if dist == "laplace":
                    llh = llh + (-jnp.abs(resid) / sigma
                                 - jnp.log(2 * sigma) + jac)
                else:
                    llh = llh + (-0.5 * (resid / sigma) ** 2
                                 - 0.5 * jnp.log(2 * jnp.pi * sigma**2)
                                 + jac)
        return {LLH: llh}


class SBMLPetabImporter(PetabImporter):
    """Zero-code PEtab import (reference AmiciPetabImporter parity,
    amici.py:26-170): point it at a PEtab YAML (or a built
    :class:`PetabProblem`) and get prior + model + kernel.

    >>> importer = SBMLPetabImporter.from_yaml("problem.yaml")
    >>> abc = ABCSMC(importer.create_model(), importer.create_prior(),
    ...              importer.create_kernel(), eps=Temperature(),
    ...              acceptor=StochasticAcceptor())
    >>> abc.new("sqlite://", importer.get_observed())
    """

    def __init__(self, problem: PetabProblem, n_steps: int = 200):
        super().__init__(problem.parameter_df)
        self.petab_problem = problem
        self.n_steps = int(n_steps)

    @classmethod
    def from_yaml(cls, path: str, n_steps: int = 200) -> "SBMLPetabImporter":
        return cls(PetabProblem.from_yaml(path), n_steps=n_steps)

    def create_model(self) -> PetabSBMLModel:
        return PetabSBMLModel(self.petab_problem, n_steps=self.n_steps)

    def create_kernel(self) -> SimpleFunctionKernel:
        """Kernel reading the model-computed log-likelihood back
        (reference amici.py:151-170)."""
        return SimpleFunctionKernel(
            lambda x, x_0: jnp.reshape(x[LLH], (-1,)),
            ret_scale=SCALE_LOG)

    def get_observed(self) -> Dict[str, float]:
        """Observed-stat placeholder: the data lives in the measurement
        table (same convention as ODEPetabImporter.get_observed)."""
        return {LLH: 0.0}
