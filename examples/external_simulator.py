"""Black-box simulators: a host Python function behind the compiled round.

Any non-JAX simulator (legacy Python, R via pyabc_tpu.external.R, shell
executables via ExternalModel) plugs in through HostFunctionModel — the
device pipeline calls back to the host for exactly the simulate stage,
and a simulator that raises self-rejects instead of killing the run.
"""

import os

import numpy as np

import pyabc_tpu as pt
from pyabc_tpu.external import HostFunctionModel

POP = int(os.environ.get("ABC_EXAMPLE_POP", 500))
GENS = int(os.environ.get("ABC_EXAMPLE_GENS", 4))


def legacy_simulator(theta: np.ndarray, seed: int) -> dict:
    """Plain numpy, one batch at a time — imagine this wraps Fortran."""
    rng = np.random.default_rng(seed)
    mu = theta[:, 0]
    return {"y": mu + 0.1 * rng.normal(size=mu.shape)}


def main():
    model = HostFunctionModel(legacy_simulator, stat_shapes={"y": ()})
    abc = pt.ABCSMC(
        model,
        pt.Distribution(mu=pt.RV("uniform", -1.0, 2.0)),
        pt.PNormDistance(p=2),
        population_size=POP,
        sampler=pt.VectorizedSampler(max_batch_size=4096),
        seed=3)
    abc.new("sqlite://", {"y": 0.4})
    history = abc.run(max_nr_populations=GENS)

    df, w = history.get_distribution()
    mu_mean = float(np.sum(df["mu"].to_numpy() * w))
    print(f"posterior mean of mu: {mu_mean:.3f} (true 0.4)")
    assert abs(mu_mean - 0.4) < 0.15
    return history


if __name__ == "__main__":
    main()
