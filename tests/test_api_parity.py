"""Public-API parity: every name the reference exports at package level
resolves in pyabc_tpu (reference pyabc/__init__.py:21-107)."""

import os
import re

import pytest

import pyabc_tpu as pt

REF_INIT = "/root/reference/pyabc/__init__.py"


def _reference_exports():
    names = set()
    with open(REF_INIT) as f:
        for line in f:
            line = line.strip()
            if line.startswith("from ") and " import " in line:
                tail = line.split(" import ", 1)[1]
                names.update(n.strip(" ,()") for n in tail.split(",")
                             if n.strip(" ,()"))
            elif line and re.match(r"^[A-Za-z_][\w]*[,)]?$", line):
                # block-closing 'Name)' lines carry the LAST export of each
                # multi-line import — stripping only ',' would drop them
                names.add(line.strip(" ,)"))
    return {n for n in names if n.isidentifier() and n != "pyABC"}


@pytest.mark.skipif(not os.path.exists(REF_INIT),
                    reason="reference checkout not present")
def test_every_reference_export_resolves():
    missing = sorted(n for n in _reference_exports()
                     if not hasattr(pt, n))
    assert not missing, f"missing package exports: {missing}"


def test_new_parity_classes_are_functional():
    import jax.numpy as jnp
    import numpy as np

    # SimpleFunctionAcceptor runs in the accept kernel form
    acc = pt.SimpleFunctionAcceptor(lambda d, eps: d <= eps * 2)
    mask, w = acc.accept(None, jnp.asarray([0.1, 5.0]), {"eps": jnp.float32(1.0)})
    assert bool(mask[0]) and not bool(mask[1])

    # RVDecorator delegates; TruncatedRV is one
    rv = pt.TruncatedRV(pt.RV("norm", 0.0, 1.0), lower=0.0)
    assert isinstance(rv, pt.RVDecorator)

    # Particle views from a Population
    pop = pt.Population(
        m=np.zeros(3, np.int32), theta=np.ones((3, 2), np.float32),
        weight=np.ones(3, np.float32) / 3, distance=np.zeros(3, np.float32))
    parts = pop.to_particles(param_names=["a", "b"])
    assert len(parts) == 3 and parts[0].parameter == {"a": 1.0, "b": 1.0}

    # scheme base
    assert isinstance(pt.AcceptanceRateScheme(), pt.TemperatureScheme)

    # RedisEvalParallelSampler is the sharded data plane
    assert issubclass(pt.RedisEvalParallelSampler, pt.ShardedSampler)


def test_round3_surface_exports():
    """Round-3 additions resolve and carry the documented API."""
    from pyabc_tpu.petab import (PetabProblem, PetabSBMLModel,
                                 SBMLPetabImporter, parse_sbml)
    from pyabc_tpu.storage import from_reference_db, to_reference_db

    assert callable(SBMLPetabImporter.from_yaml)
    assert callable(PetabProblem.from_yaml)
    assert callable(parse_sbml)
    assert callable(to_reference_db) and callable(from_reference_db)
    assert callable(pt.History.from_reference_db)
    assert callable(pt.History.to_reference_db)

    # deferred-proposal contract points
    from pyabc_tpu.sampler.base import Sampler, fetch_to_host
    from pyabc_tpu.sampler.rounds import RoundKernel
    assert RoundKernel.generation_round.supports_deferred_proposal
    assert hasattr(Sampler(), "record_proposal_density")
    assert callable(fetch_to_host)
