"""Rule ``span-pairs``: every explicit span ``begin()`` has an
``end()``.

``telemetry/spans.py`` offers two APIs: the ``with span(...)`` context
manager (cannot leak) and the explicit ``tok = spans.begin(...)`` /
``spans.end(tok)`` pair for spans that outlive a scope — ingest queue
tickets, the ``GenStream`` per-generation span.  An explicit begin
whose token is dropped, or whose token is never passed to ``end()``
anywhere in the same file, produces a span that silently never closes:
the Chrome trace shows an open track to the end of the process, the
fleet merge inherits the garbage, and — worse — nobody notices until a
trace is actually read.

Checks (package-wide, ``telemetry/spans.py`` itself exempt):

- a ``spans.begin(...)`` / ``telemetry.begin(...)`` call must assign
  its token (``tok = spans.begin(...)``) — a bare call discards the
  only handle that can ever close the span;
- the assignment target's name must appear inside some ``spans.end(...)``
  argument in the SAME file (helpers like ``_end_span`` keep the
  ``end()`` call in-file, so this stays a per-file property).

Legacy suppression: ``# span-ok`` on the line;
``# graftlint: allow(span-pairs)`` also works.
"""

from __future__ import annotations

import os
import re
import sys

from ..core import Finding, Rule, default_package_root, register

SUPPRESS = "# span-ok"

#: files that define the API rather than use it
EXEMPT = {"telemetry/spans.py"}

_BEGIN = re.compile(r"(?:spans|telemetry)\.begin\s*\(")
_ASSIGNED_BEGIN = re.compile(
    r"^\s*(?P<target>[A-Za-z_][\w.]*)\s*=\s*(?:spans|telemetry)\.begin\s*\(")
_END = re.compile(r"(?:spans|telemetry)\.end\s*\((?P<arg>[^)]*)")


def _package_root(root: str = None) -> str:
    return root if root is not None else default_package_root()


def _py_files(root: str):
    for dirpath, _, names in os.walk(root):
        for name in sorted(names):
            if name.endswith(".py"):
                path = os.path.join(dirpath, name)
                yield os.path.relpath(path, root).replace(os.sep, "/"), path


def check(root: str = None) -> list:
    """Scan the package; returns ``[(relpath, lineno, line), ...]``
    violations (empty = clean)."""
    root = _package_root(root)
    violations = []
    for rel, path in _py_files(root):
        if rel in EXEMPT:
            continue
        with open(path, encoding="utf-8") as f:
            lines = f.read().splitlines()
        end_args = " ".join(m.group("arg")
                            for line in lines
                            for m in [_END.search(line.split("#", 1)[0])]
                            if m)
        for lineno, line in enumerate(lines, 1):
            if SUPPRESS in line:
                continue
            code = line.split("#", 1)[0]
            if not _BEGIN.search(code):
                continue
            m = _ASSIGNED_BEGIN.match(code)
            if m is None:
                violations.append((rel, lineno, line.rstrip()))
                continue
            # 'self._q_span' -> '_q_span': the attribute travels across
            # objects (ticket._q_span), the receiver name does not
            token = m.group("target").rsplit(".", 1)[-1]
            if token not in end_args:
                violations.append((rel, lineno, line.rstrip()))
    return violations


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    root = argv[0] if argv else None
    violations = check(root)
    if not violations:
        print("span pairs: clean (every explicit begin() has a "
              "matching end())")
        return 0
    print("span-pair violations (assign the begin() token and pass it "
          f"to spans.end() in the same file, or justify with "
          f"'{SUPPRESS}'):")
    for rel, lineno, line in violations:
        print(f"  pyabc_tpu/{rel}:{lineno}: {line.strip()}")
    return 1


@register
class SpanPairsRule(Rule):
    id = "span-pairs"
    description = ("explicit spans.begin() tokens are assigned and "
                   "closed by an in-file spans.end()")

    def run(self, tree):
        prefix = tree.package_rel_prefix()
        return [Finding(self.id, f"{prefix}/{rel}", lineno, line.strip())
                for rel, lineno, line in check(tree.package_root)]
