"""Model selection: two competing Gaussian models.

The reference's central model-selection example: the posterior model
probabilities converge to the analytic evidence ratio as epsilon shrinks.
"""

import os

import pyabc_tpu as pt
from pyabc_tpu.models import make_two_gaussians_problem

POP = int(os.environ.get("ABC_EXAMPLE_POP", 2000))
GENS = int(os.environ.get("ABC_EXAMPLE_GENS", 5))


def main():
    models, priors, distance, observed, posterior_fn = \
        make_two_gaussians_problem()
    abc = pt.ABCSMC(models, priors, distance, population_size=POP, seed=2)
    abc.new("sqlite://", observed)
    history = abc.run(max_nr_populations=GENS)

    probs = history.get_model_probabilities(history.max_t)
    expected = posterior_fn(1.0)
    p_b = float(probs.get(1, 0.0))  # keyed by model index, not position
    print(f"P(model B): {p_b:.3f} (analytic {expected:.3f})")
    assert abs(p_b - expected) < 0.15
    return history


if __name__ == "__main__":
    main()
