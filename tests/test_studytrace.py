"""Tier-1 gate for study-level distributed tracing (serve/tracing.py +
telemetry/studytrace.py; docs/observability.md "Tracing a study").

Pins the tracing contracts end to end:

- lifecycle events: every queue transition appends its event, the
  trace id rides the ticket payload from submit to tombstone, and the
  per-partition log is torn-tail tolerant;
- critical-path folding: phase segments are monotone, non-overlapping,
  and sum to the study's end-to-end latency; a bounce shows up as a
  second queue-wait segment, never a hole;
- the served tombstone carries the folded phase block, and the phases
  sum to the tombstone's own wall clock;
- Chrome export: exactly one complete-event span per lifecycle phase;
- trace-off mode (``PYABC_TPU_SERVE_TRACE=0``) leaves the serve root
  byte-identical to the pre-tracing layout: no trace directory, no
  trace id in payloads, no trace block in tombstones;
- GC: old trace segments are swept at segment granularity, and dead
  workers' SLO latency snapshots are reaped from ``slo/``;
- fleet accounting: flat-bucket latency counters roll up into
  histograms with percentiles, and the SLO ledger splits admitted
  completions into over/under/shed.
"""

import json
import os
import sys
import time

import pytest

_REPO = os.path.abspath(os.path.join(os.path.dirname(__file__),
                                     os.pardir))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

import pyabc_tpu as pt  # noqa: E402
from pyabc_tpu.serve import (ServeWorker, StudyQueue,  # noqa: E402
                             StudySpec, study_digest)
from pyabc_tpu.serve.tracing import (EVENTS, TRACE_ENV,  # noqa: E402
                                     TraceLog)
from pyabc_tpu.telemetry import REGISTRY  # noqa: E402
from pyabc_tpu.telemetry import studytrace  # noqa: E402
from pyabc_tpu.telemetry.studytrace import (StudyTrace,  # noqa: E402
                                            fold_phases, fold_segments,
                                            latency_histogram,
                                            slo_ledger, waterfall_text)


def _model(key, theta):
    import jax
    noise = 0.1 * jax.random.normal(key, (theta.shape[0], 1))
    return {"y": theta[:, :1] + noise}


def _spec(pop=100, seed=0, tenant="default", y=0.4, **kw):
    return StudySpec(
        model=_model,
        prior=pt.Distribution(mu=pt.RV("uniform", -1.0, 2.0)),
        observed={"y": float(y)}, population_size=pop,
        seed=seed, tenant=tenant,
        max_generations=kw.pop("max_generations", 2), **kw)


def _synthetic_lifecycle(t0=1000.0, tid="t" * 32):
    """A full single-worker lifecycle with easy round numbers."""
    steps = (("submitted", 0.0), ("queued", 0.0), ("claimed", 1.0),
             ("batched", 1.5), ("dispatched", 2.0), ("drained", 6.0),
             ("published", 6.5), ("tombstoned", 7.0))
    return [{"trace_id": tid, "event": ev, "unix": t0 + dt,
             "mono": dt, "ticket": "tk1", "digest": "d1",
             "worker": "w1"} for ev, dt in steps]


# ---------------------------------------------------------------------------
# lifecycle events on the queue path
# ---------------------------------------------------------------------------

def test_queue_transitions_emit_lifecycle_events(tmp_path):
    q = StudyQueue(root=str(tmp_path))
    t = q.submit(_spec(seed=1))
    assert t.trace_id, "trace id not stamped at submit"
    c = q.claim("w_a")
    assert c.trace_id == t.trace_id
    q.complete(c, wall_s=0.01, engine="solo")
    events = q.trace.events_for(t.id)
    names = [e["event"] for e in events]
    assert names == ["submitted", "queued", "claimed", "tombstoned"]
    assert all(e["trace_id"] == t.trace_id for e in events)
    queued = events[1]
    assert isinstance(queued["partition"], int)
    assert events[2]["worker"] == "w_a"
    assert events[3]["state"] == "done"
    # the same events resolve by trace id and by digest
    assert q.trace.events_for(t.trace_id) == events
    assert [e["event"] for e in q.trace.events_for(t.digest)] == names


def test_bounce_keeps_one_continuous_trace(tmp_path):
    q = StudyQueue(root=str(tmp_path))
    t = q.submit(_spec(seed=2))
    c1 = q.claim("w_dead")
    assert q.requeue(c1, worker="w_dead", error="kill -9")
    c2 = q.claim("w_rescue")
    assert c2.trace_id == t.trace_id
    q.complete(c2, wall_s=0.01, engine="solo")
    names = [e["event"] for e in q.trace.events_for(t.trace_id)]
    assert names == ["submitted", "queued", "claimed", "requeued",
                     "claimed", "tombstoned"]


def test_unknown_event_name_raises(tmp_path):
    log = TraceLog(str(tmp_path))
    with pytest.raises(ValueError):
        log.emit(log.new_id(), "vanished")
    assert "vanished" not in EVENTS


def test_torn_tail_is_skipped_not_fatal(tmp_path):
    log = TraceLog(str(tmp_path))
    tid = log.new_id()
    log.emit(tid, "submitted", digest="d", ticket="tk")
    log.emit(tid, "claimed", digest="d", ticket="tk", worker="w")
    # a crashed emitter's torn last line
    (seg,) = [os.path.join(dp, n)
              for dp, _, ns in os.walk(log.root)
              for n in ns if n.endswith(".jsonl")]
    with open(seg, "a", encoding="utf-8") as f:
        f.write('{"trace_id": "' + tid + '", "event": "drai')
    names = [e["event"] for e in log.events_for(tid)]
    assert names == ["submitted", "claimed"]


# ---------------------------------------------------------------------------
# critical-path folding
# ---------------------------------------------------------------------------

def test_fold_segments_monotone_and_exhaustive():
    events = _synthetic_lifecycle()
    segs = fold_segments(events)
    assert [s["phase"] for s in segs] == [
        "queue_wait_s", "claim_to_dispatch_s", "compile_s",
        "device_s", "drain_s", "publish_s"]
    for a, b in zip(segs, segs[1:]):
        assert abs((a["t0_unix"] + a["dur_s"]) - b["t0_unix"]) < 1e-9
    phases = fold_phases(events)
    assert phases["queue_wait_s"] == 1.0
    assert phases["claim_to_dispatch_s"] == 0.5
    assert phases["compile_s"] == 0.5
    assert phases["device_s"] == 4.0
    assert phases["drain_s"] == 0.5
    assert phases["publish_s"] == 0.5
    assert phases["total_s"] == 7.0
    assert sum(phases[p] for p in studytrace.PHASES) == pytest.approx(
        phases["total_s"])
    assert phases["bounces"] == 0 and phases["events_n"] == len(events)


def test_fold_bounce_sums_queue_waits():
    tid = "b" * 32
    steps = (("submitted", 0.0), ("claimed", 1.0), ("requeued", 3.0),
             ("claimed", 5.0), ("published", 6.0), ("tombstoned", 6.5))
    events = [{"trace_id": tid, "event": ev, "unix": 100.0 + dt,
               "mono": dt} for ev, dt in steps]
    phases = fold_phases(events)
    # 0→1 (first wait) + 3→5 (post-bounce wait), summed
    assert phases["queue_wait_s"] == 3.0
    assert phases["bounces"] == 1
    segs = [s for s in fold_segments(events)
            if s["phase"] == "queue_wait_s"]
    assert len(segs) == 2


def test_instant_markers_do_not_move_the_phase_machine():
    events = _synthetic_lifecycle()
    with_markers = events + [
        {"trace_id": events[0]["trace_id"], "event": "rescued",
         "unix": 1001.2, "mono": 1.2, "resumed_from_gen": 1}]
    assert fold_segments(with_markers) == fold_segments(events)


# ---------------------------------------------------------------------------
# served studies: tombstone block, assembly, export
# ---------------------------------------------------------------------------

def test_served_tombstone_carries_summing_phases(tmp_path, monkeypatch):
    monkeypatch.setenv("PYABC_TPU_SERVE_MULTIPLEX", "1")
    monkeypatch.setenv("PYABC_TPU_SERVE_SLO_P99_MS", "600000")
    q = StudyQueue(root=str(tmp_path))
    spec = _spec(seed=3)
    t = q.submit(spec)
    worker = ServeWorker(root=str(tmp_path), worker_id="w_e2e",
                         run_mode="classic")
    assert worker.run_forever(q, once=True) == 1
    with open(os.path.join(q.root, "done", f"{t.id}.json"),
              encoding="utf-8") as f:
        tomb = json.load(f)
    block = tomb["trace"]
    assert block["trace_id"] == t.trace_id
    assert block["worker"] == "w_e2e" and block["bounces"] == 0
    phases = block["phases"]
    assert all(phases[p] >= 0.0 for p in studytrace.PHASES)
    assert phases["device_s"] > 0.0
    assert sum(phases[p] for p in studytrace.PHASES) == pytest.approx(
        phases["total_s"], abs=0.1)
    # assembled view agrees with the tombstone and exports cleanly
    trace = StudyTrace.assemble(str(tmp_path), t.id)
    assert trace.trace_id == t.trace_id
    for ev in ("submitted", "queued", "claimed", "batched",
               "dispatched", "drained", "published", "tombstoned"):
        assert ev in trace.event_names()
    out = os.path.join(str(tmp_path), "study.trace.json")
    trace.write_chrome_trace(out)
    with open(out, encoding="utf-8") as f:
        chrome = json.load(f)
    spans_x = [e["name"] for e in chrome if e.get("ph") == "X"]
    assert sorted(spans_x) == sorted(
        f"study.{p[:-2]}" for p in studytrace.PHASES), (
        "expected exactly one span per lifecycle phase")
    # the SLO ledger saw one admitted under-SLO completion
    snap = REGISTRY.to_dict()
    assert snap.get("serve_slo_under_total", 0) >= 1
    assert snap.get("serve_latency_ms_le_inf", 0) >= 1
    # the abc-top waterfall renders one bar per phase
    lines = waterfall_text(trace)
    assert len(lines) == 1 + len(studytrace.PHASES)
    assert "bounces 0" in lines[0]


def test_duplicate_submission_traces_as_cache_hit(tmp_path,
                                                 monkeypatch):
    monkeypatch.setenv("PYABC_TPU_SERVE_MULTIPLEX", "1")
    q = StudyQueue(root=str(tmp_path))
    spec = _spec(seed=3)
    q.submit(spec)
    worker = ServeWorker(root=str(tmp_path), worker_id="w_hit",
                         run_mode="classic")
    assert worker.run_forever(q, once=True) == 1
    dup = q.submit(_spec(seed=3))
    assert worker.run_forever(q, once=True) == 1
    names = StudyTrace.assemble(str(tmp_path), dup.id).event_names()
    assert "cache_hit" in names and "dispatched" not in names


# ---------------------------------------------------------------------------
# trace-off mode: byte-identical serve root
# ---------------------------------------------------------------------------

def test_trace_off_leaves_no_fingerprint(tmp_path, monkeypatch):
    monkeypatch.setenv(TRACE_ENV, "0")
    q = StudyQueue(root=str(tmp_path))
    t = q.submit(_spec(seed=4))
    assert t.trace_id is None
    assert q.trace.new_id() is None
    c = q.claim("w_off")
    q.complete(c, wall_s=0.01, engine="solo")
    assert not os.path.exists(q.trace.root), (
        "trace directory created while tracing is off")
    with open(os.path.join(q.root, "done", f"{t.id}.json"),
              encoding="utf-8") as f:
        tomb = json.load(f)
    assert "trace_id" not in tomb and "trace" not in tomb


# ---------------------------------------------------------------------------
# GC: trace segments and dead workers' SLO snapshots
# ---------------------------------------------------------------------------

def test_trace_sweep_unlinks_old_segments(tmp_path):
    log = TraceLog(str(tmp_path))
    tid = log.new_id()
    log.emit(tid, "submitted", digest="d", ticket="tk")
    (seg,) = [os.path.join(dp, n)
              for dp, _, ns in os.walk(log.root)
              for n in ns if n.endswith(".jsonl")]
    assert log.sweep(retain_s=3600.0) == 0, "fresh segment swept"
    old = time.time() - 7200.0
    os.utime(seg, (old, old))
    assert log.sweep(retain_s=3600.0) == 1
    assert not os.path.exists(seg)
    assert log.sweep(retain_s=0.0) == 0  # 0 disables


def test_sweep_snapshots_reaps_dead_workers(tmp_path):
    from pyabc_tpu.serve.admission import (publish_latency_snapshot,
                                           sweep_snapshots)
    root = str(tmp_path)
    for wid in ("host_1", "host_2", "host_3"):
        publish_latency_snapshot(root, wid, [10.0, 20.0])
    slo_dir = os.path.join(root, "slo")
    assert len(os.listdir(slo_dir)) == 3
    # host_2 is dead per liveness; host_3's snapshot is stale (the
    # freshness judgment reads the payload's own ts, not mtime)
    publish_latency_snapshot(root, "host_3", [10.0],
                             now=time.time() - 7200.0)
    swept = sweep_snapshots(
        root, liveness={"host_1": True, "host_2": False},
        fresh_s=3600.0)
    assert swept == 2
    assert sorted(os.listdir(slo_dir)) == ["host_1.json"]


def test_scheduler_tick_reports_trace_gc(tmp_path, monkeypatch):
    from pyabc_tpu.sched import Scheduler
    monkeypatch.delenv("PYABC_TPU_RUN_DIR", raising=False)
    q = StudyQueue(root=str(tmp_path))
    q.submit(_spec(seed=5))
    rep = Scheduler(run_dir=None, queue=q).tick()
    assert rep["trace_swept"] == 0 and rep["slo_swept"] == 0


# ---------------------------------------------------------------------------
# fleet accounting: histograms + SLO ledger
# ---------------------------------------------------------------------------

def test_latency_histogram_rollup_and_percentiles():
    rollup = {"serve_latency_ms_le_inf": 100.0,
              "serve_latency_ms_sum_total": 20000.0}
    # cumulative le counters: 60 under 100ms, 99 under 1s, all under 10s
    for b, n in ((5, 0), (10, 0), (25, 10), (50, 30), (100, 60),
                 (250, 80), (500, 95), (1000, 99), (2500, 99),
                 (5000, 99), (10000, 100)):
        rollup[f"serve_latency_ms_le_{b}"] = float(n)
    hist = latency_histogram(rollup, "serve_latency_ms")
    assert hist["count"] == 100.0 and hist["sum_ms"] == 20000.0
    assert hist["p50_ms"] == 100.0
    assert hist["p99_ms"] == 1000.0


def test_record_study_slo_burns_and_ledger():
    before = REGISTRY.to_dict()

    def delta(key):
        return (REGISTRY.to_dict().get(key, 0.0)
                - before.get(key, 0.0))

    studytrace.record_study_slo(50.0, 10.0, slo_p99_ms=200.0)
    studytrace.record_study_slo(900.0, 700.0, slo_p99_ms=200.0)
    assert delta("serve_slo_under_total") == 1
    assert delta("serve_slo_over_total") == 1
    assert delta("serve_latency_ms_le_inf") == 2
    assert delta("serve_latency_ms_le_100") == 1  # only the 50ms study
    snap = REGISTRY.to_dict()
    ledger = slo_ledger(snap)
    assert ledger["slo_p99_ms"] == 200.0
    assert ledger["over"] >= 1 and ledger["under"] >= 1
    assert 0.0 < ledger["burn_rate"] <= 1.0


def test_prometheus_rendering_reassembles_histogram(monkeypatch,
                                                    tmp_path):
    from pyabc_tpu.telemetry import aggregate
    studytrace.record_study_slo(42.0, 7.0, slo_p99_ms=500.0)
    snap = {"schema_version": aggregate.SCHEMA_VERSION,
            "host": "h", "pid": 1, "metrics": REGISTRY.to_dict()}
    tdir = aggregate.telemetry_dir(str(tmp_path))
    os.makedirs(tdir, exist_ok=True)
    with open(os.path.join(tdir, "snap_h_1.json"), "w",
              encoding="utf-8") as f:
        json.dump(snap, f)
    roll = aggregate.fleet_rollup(str(tmp_path))
    serve = roll["serve"]
    assert serve["latency"]["count"] >= 1
    assert serve["slo"]["slo_p99_ms"] == 500.0
    text = aggregate.render_prometheus(str(tmp_path))
    assert 'pyabc_tpu_serve_latency_ms_bucket{le="+Inf"}' in text
    assert "pyabc_tpu_serve_latency_ms_count" in text
    # the serve section never leaks the flat per-bucket counters as
    # raw lines (the generic pyabc_tpu_fleet_* dump still carries
    # every registry key — that is its contract)
    assert "pyabc_tpu_serve_latency_ms_le_" not in text
