SITE_DISPATCH = "dispatch"


def run(self, fn, *args):
    return self._retry.call(SITE_DISPATCH, fn, *args)
