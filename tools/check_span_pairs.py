#!/usr/bin/env python
"""Compatibility shim: this check now lives in the unified graftlint
framework (tools/lint/rules/span_pairs.py).  Kept so existing invocations
and muscle memory (`python tools/check_span_pairs.py`) keep working; prefer
`abc-lint` which runs all rules in one process."""

from __future__ import annotations

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from tools.lint.rules.span_pairs import check, main  # noqa: E402,F401

if __name__ == "__main__":
    sys.exit(main())
