"""Closed-loop load generator for the serving data plane.

Drives a fleet of ``abc-serve`` workers through the real submit path
(:meth:`StudyQueue.submit` → partitioned ``pending/`` → worker claim →
``done/`` tombstone) with the closed-loop discipline of LLM-serving
benchmarks: N concurrent clients, each submitting one study, waiting
for its tombstone, then thinking for an exponentially-distributed
pause — Poisson arrivals at a controlled aggregate rate, never an
unbounded open loop that measures nothing but queue growth.

Each client records per-study end-to-end latency (submit → settled
tombstone) and the engine the study was served from (the tombstone's
``engine`` field: ``cache`` = tier-1, ``cache_t2`` = shared tier-2,
``multiplex``/``solo`` = dispatched).  When tracing is on the
tombstone also carries the server-attributed phase breakdown
(``trace.phases``); the report compares client-observed latency
against the server-attributed total and prints the gap explicitly —
it is the tombstone-poll artifact (bounded by ``poll_s``), not
hidden inside either number — plus queue-wait percentiles.  Shed responses
(:class:`ServeOverloaded`) honor the computed ``retry_after_s``
(capped) and count into the shed rate; quota/backpressure rejections
retry after a short fixed pause.

The report feeds ``bench.py bench_serve_load`` (the ``serve_load_*``
sentinel rows) and is usable standalone::

    python tools/loadgen.py --serve-dir /mnt/fleet/serve \
        --studies 10000 --clients 32 --rate-hz 200

The generator is deliberately dumb about the fleet: it only touches
the queue directories, so it load-tests whatever is draining them —
one in-process worker thread in tests, platform-managed subprocess
fleets in bench, a real TPU fleet in production.
"""

from __future__ import annotations

import json
import os
import random
import sys
import threading
import time
from typing import Callable, List, Optional, Sequence

_REPO = os.path.abspath(os.path.join(os.path.dirname(__file__),
                                     os.pardir))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from pyabc_tpu.serve.admission import ServeOverloaded  # noqa: E402
from pyabc_tpu.serve.queue import QueueFull, StudyQueue  # noqa: E402

#: cap on how long a shed's retry_after_s is honored (a pathological
#: quote must not stall the run)
_MAX_RETRY_S = 5.0

#: fixed pause after a quota/backpressure rejection
_REJECT_RETRY_S = 0.05


def _percentile(values: Sequence[float], q: float) -> float:
    if not values:
        return 0.0
    vs = sorted(values)
    idx = min(int(q * len(vs)), len(vs) - 1)
    return float(vs[idx])


class ClosedLoopLoadGen:
    """N closed-loop clients over one study queue.

    ``specs`` is the submission pool; each client draws from it with
    its own seeded RNG (duplicates in the pool are the point — they
    exercise the cache tiers).  ``rate_hz`` is the target AGGREGATE
    arrival rate: each client thinks ``Exp(rate_hz / clients)``
    between completions, so arrivals are Poisson at ``rate_hz`` when
    the fleet keeps up and gracefully throttle to fleet capacity when
    it does not (closed loop).  ``rate_hz=None`` disables think time
    (max-pressure mode).

    ``unique=True`` flips the draw to a seed-shuffled pass over the
    pool without replacement (cycling if ``n_studies`` exceeds it):
    every submission is a FRESH study, so the fleet's dispatch path —
    not the cache tiers — is what gets priced.  That is the traffic
    shape the continuous-batching A/B needs: lane work per arrival,
    mixed durations, no dedup shortcut."""

    def __init__(self, queue: StudyQueue, specs: Sequence,
                 n_studies: int, clients: int = 8,
                 rate_hz: Optional[float] = None, seed: int = 0,
                 poll_s: float = 0.005, study_timeout_s: float = 120.0,
                 unique: bool = False,
                 on_progress: Optional[Callable[[int], None]] = None):
        self.queue = queue
        self.specs = list(specs)
        self.n_studies = int(n_studies)
        order = list(range(len(self.specs)))
        random.Random(seed).shuffle(order)
        self._order = order if unique else None
        self.clients = max(int(clients), 1)
        self.rate_hz = rate_hz
        self.seed = int(seed)
        self.poll_s = float(poll_s)
        self.study_timeout_s = float(study_timeout_s)
        self.on_progress = on_progress
        self._lock = threading.Lock()
        self._submitted = 0
        self._lat_ms: List[float] = []
        self._server_ms: List[float] = []
        self._queue_wait_ms: List[float] = []
        self._engines: dict = {}
        self._sheds = 0
        self._shed_wait_s = 0.0
        self._rejects = 0
        self._failed = 0
        self._timeouts = 0

    # ---- client loop -----------------------------------------------------

    def _take_slot(self) -> Optional[int]:
        """Claim the next submission slot; its index drives the
        without-replacement draw in ``unique`` mode."""
        with self._lock:
            if self._submitted >= self.n_studies:
                return None
            slot = self._submitted
            self._submitted += 1
            return slot

    def _settled(self, ticket) -> Optional[dict]:
        """The ticket's tombstone payload once it reaches done/failed,
        else ``None`` while still in flight."""
        for state in ("done", "failed"):
            path = os.path.join(self.queue.root, state,
                                f"{ticket.id}.json")
            try:
                with open(path, encoding="utf-8") as f:
                    payload = json.load(f)
            except (OSError, ValueError):
                continue  # not settled (or torn mid-write): keep waiting
            payload["_state"] = state
            return payload
        return None

    def _run_client(self, idx: int):
        rng = random.Random((self.seed << 16) ^ idx)
        think_hz = (None if not self.rate_hz
                    else self.rate_hz / self.clients)
        while True:
            slot = self._take_slot()
            if slot is None:
                break
            if self._order is not None:
                spec = self.specs[self._order[slot % len(self._order)]]
            else:
                spec = self.specs[rng.randrange(len(self.specs))]
            t0 = time.perf_counter()
            ticket = None
            deadline = time.monotonic() + self.study_timeout_s
            while ticket is None:
                try:
                    ticket = self.queue.submit(spec)
                except ServeOverloaded as shed:
                    wait = min(max(shed.retry_after_s, 0.01),
                               _MAX_RETRY_S)
                    with self._lock:
                        self._sheds += 1
                        self._shed_wait_s += wait
                    time.sleep(wait)
                except QueueFull:
                    with self._lock:
                        self._rejects += 1
                    time.sleep(_REJECT_RETRY_S)
                if time.monotonic() > deadline:
                    break
            if ticket is None:
                with self._lock:
                    self._timeouts += 1
                continue
            tomb = None
            while time.monotonic() < deadline:
                tomb = self._settled(ticket)
                if tomb is not None:
                    break
                time.sleep(self.poll_s)
            lat_ms = (time.perf_counter() - t0) * 1e3
            with self._lock:
                if tomb is None:
                    self._timeouts += 1
                elif tomb["_state"] == "failed":
                    self._failed += 1
                else:
                    self._lat_ms.append(lat_ms)
                    eng = str(tomb.get("engine", "unknown"))
                    self._engines[eng] = self._engines.get(eng, 0) + 1
                    phases = (tomb.get("trace") or {}).get("phases")
                    if phases:
                        self._server_ms.append(
                            float(phases.get("total_s", 0.0)) * 1e3)
                        self._queue_wait_ms.append(
                            float(phases.get("queue_wait_s", 0.0)) * 1e3)
                done = len(self._lat_ms)
            if self.on_progress is not None:
                self.on_progress(done)
            if think_hz:
                time.sleep(rng.expovariate(think_hz))

    # ---- driver ----------------------------------------------------------

    def run(self) -> dict:
        t0 = time.perf_counter()
        threads = [threading.Thread(target=self._run_client, args=(i,),
                                    daemon=True,
                                    name=f"loadgen-{i}")
                   for i in range(self.clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall_s = time.perf_counter() - t0
        with self._lock:
            lats = list(self._lat_ms)
            server_ms = list(self._server_ms)
            queue_wait_ms = list(self._queue_wait_ms)
            engines = dict(self._engines)
            sheds, rejects = self._sheds, self._rejects
            failed, timeouts = self._failed, self._timeouts
            shed_wait_s = self._shed_wait_s
        completed = len(lats)
        attempts = completed + failed + timeouts + sheds
        t1 = engines.get("cache", 0)
        t2 = engines.get("cache_t2", 0)
        client_p50 = _percentile(lats, 0.50)
        server_p50 = _percentile(server_ms, 0.50)
        # Client-observed minus server-attributed at the median: the
        # tombstone-poll artifact (bounded by poll_s plus scheduling
        # jitter).  Reported, never folded into either latency.
        gap_ms = client_p50 - server_p50 if server_ms else 0.0
        return {
            "studies_per_s": round(completed / wall_s, 3) if wall_s
            else 0.0,
            "p50_ms": round(client_p50, 3),
            "p99_ms": round(_percentile(lats, 0.99), 3),
            "server_p50_ms": round(server_p50, 3),
            "server_p99_ms": round(_percentile(server_ms, 0.99), 3),
            "client_server_gap_ms": round(gap_ms, 3),
            "queue_wait_p50_ms": round(
                _percentile(queue_wait_ms, 0.50), 3),
            "queue_wait_p99_ms": round(
                _percentile(queue_wait_ms, 0.99), 3),
            "traced": len(server_ms),
            "shed_rate": round(sheds / attempts, 5) if attempts
            else 0.0,
            "cache_hit_tier1": round(t1 / completed, 5) if completed
            else 0.0,
            "cache_hit_tier2": round(t2 / completed, 5) if completed
            else 0.0,
            "completed": completed,
            "failed": failed,
            "timeouts": timeouts,
            "sheds": sheds,
            "shed_wait_s": round(shed_wait_s, 3),
            "rejected": rejects,
            "wall_s": round(wall_s, 3),
            "clients": self.clients,
            "rate_hz": self.rate_hz,
            "engines": engines,
        }


def main():  # pragma: no cover - thin CLI over ClosedLoopLoadGen
    import argparse

    import pyabc_tpu as pt
    from pyabc_tpu.serve.spec import StudySpec

    ap = argparse.ArgumentParser(
        description="Closed-loop load generator for abc-serve fleets")
    ap.add_argument("--serve-dir", default=None)
    ap.add_argument("--studies", type=int, default=10_000)
    ap.add_argument("--clients", type=int, default=16)
    ap.add_argument("--rate-hz", type=float, default=None)
    ap.add_argument("--pool", type=int, default=16,
                    help="distinct specs in the submission pool")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    def _model(key, theta):
        import jax
        noise = 0.1 * jax.random.normal(key, (theta.shape[0], 1))
        return {"y": theta[:, :1] + noise}

    pops = (100, 300, 1000)
    specs = [StudySpec(
        model=_model,
        prior=pt.Distribution(mu=pt.RV("uniform", -1.0, 2.0)),
        observed={"y": 0.1 * (i % 4)},
        population_size=pops[i % len(pops)],
        seed=i, max_generations=2,
        tenant=f"tenant{i % 3}") for i in range(args.pool)]
    queue = StudyQueue(root=args.serve_dir)
    gen = ClosedLoopLoadGen(queue, specs, n_studies=args.studies,
                            clients=args.clients, rate_hz=args.rate_hz,
                            seed=args.seed)
    print(json.dumps(gen.run(), indent=2))


if __name__ == "__main__":  # pragma: no cover
    main()
