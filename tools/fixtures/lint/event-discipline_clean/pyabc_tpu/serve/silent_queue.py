"""The event-discipline_bad violations, silenced every sanctioned way:
emit-in-body, delegation to an emitting transition, the ``# event-ok``
marker, and the generic graftlint allow."""

import os
import time


class TracedQueue:
    def submit(self, spec):
        # the real contract: the transition logs itself
        path = os.path.join("pending", f"{spec.digest}.json")
        with open(path, "w") as f:
            f.write("{}")
        self.trace.emit(spec.trace_id, "submitted", digest=spec.digest)
        return path

    def requeue(self, ticket, worker=None, error=None):
        dest = os.path.join("pending", f"{ticket.id}.json")
        os.rename(ticket.path, dest)
        ticket.path = dest
        self.trace.emit(ticket.trace_id, "requeued", worker=worker)
        return True

    def _move(self, ticket, state, extra):
        payload = dict(extra)
        payload["moved_unix"] = time.time()
        dest = os.path.join(state, f"{ticket.id}.json")
        os.rename(ticket.path, dest)
        self.trace.emit(ticket.trace_id, "tombstoned", state=state)
        return dest

    def complete(self, ticket, wall_s=0.0):
        # delegation: _move owns the tombstoned event
        return self._move(ticket, "done", {"wall_s": wall_s})

    def fail(self, ticket, error):  # event-ok
        # event intentionally owned by the caller's batch emitter
        return os.path.join("failed", f"{ticket.id}.json")

    def quarantine(self, ticket, error):  # graftlint: allow(event-discipline)
        return os.path.join("failed", f"{ticket.id}.json")
