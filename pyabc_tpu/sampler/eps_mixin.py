"""EPSMixin: the DYN eval-parallel scheduler shared by futures samplers.

Parity: pyabc/sampler/eps_mixin.py:6-123 — submit batches while
``running < min(client_max_jobs, client_cores())``, harvest completed
futures, account results in SUBMISSION order (the de-biasing protocol:
results are consumed by submission id, so a fast straggler cannot jump the
queue and bias the population toward short-running simulations), cancel
stragglers once n are accepted.

The per-batch work is a compiled round function (a fixed-shape batch of B
candidates), not a single-particle closure — each future returns a whole
``RoundResult``.  Shared by :class:`ConcurrentFutureSampler`
(pyabc_tpu/sampler/mapping.py) and :class:`DaskDistributedSampler`
(pyabc_tpu/sampler/dask_sampler.py), exactly the reference's class
topology (concurrent_future.py:5-71, dask_sampler.py:7-71).
"""

from __future__ import annotations

import logging
import time

import jax
import numpy as np

from ..resilience import retry as _retry
from .base import Sample, fetch_to_host

logger = logging.getLogger("ABC.Sampler")


class EPSMixin:
    """Scheduling core over an abstract futures client.

    Concrete samplers provide:

    - ``_submit(fn, seed) -> future`` — future must expose ``result()``,
      ``done()`` and ``cancel()``
    - ``client_cores() -> int`` — parallelism of the backing cluster
    - optionally ``_wait_any(futures) -> future`` — blocking wait for any
      completed future (default: poll ``done()``)

    plus attributes ``client_max_jobs`` and ``batch_size``.
    """

    client_max_jobs: int = 8
    batch_size: int = 1

    def _submit(self, fn, seed):
        raise NotImplementedError

    def client_cores(self) -> int:
        return self.client_max_jobs

    def _wait_any(self, futures):
        """Return any completed future (default: poll; backends with a
        native blocking wait override this)."""
        while True:
            for fut in futures:
                if fut.done():
                    return fut
            time.sleep(0.001)

    def _cancel(self, fut):
        try:
            fut.cancel()
        except Exception:  # cancellation is best-effort on every backend
            pass

    def _recover(self):
        """Recover from a broken backend (all in-flight work lost).

        Return True if the backend was rebuilt and sampling may continue
        (the scheduler resubmits lost work), False to re-raise.  Default:
        not recoverable.  Parity: the reference detects worker death
        (multicorebase.py:78-105 ``get_if_worker_healthy``) and raises;
        samplers that own their executor can do better and rebuild it.
        """
        return False

    #: abort after this many consecutive failed batches with no progress —
    #: distinguishes a persistently-crashing model from sporadic failures
    #: (the reference loops forever on an always-raising model; see
    #: redis_eps/cli.py:141-145 which only warns per failure)
    max_consecutive_failures: int = 64

    #: resubmissions of the SAME batch after a transient infrastructure
    #: failure (resilience/retry.py classification) before it is written
    #: off as a genuine model failure
    max_transient_retries: int = 3

    def sample_until_n_accepted(self, n, round_fn, key, params,
                                max_eval=np.inf, all_accepted=False,
                                **kwargs) -> Sample:
        sample = Sample(record_rejected=self.record_rejected,
                        max_records=self.max_records)
        B = self.batch_size

        def eval_batch(seed: int):
            k = jax.random.fold_in(key, seed)
            return seed, fetch_to_host(round_fn(
                k, params, B, **({"all_accepted": True}
                                 if all_accepted else {})))

        max_jobs = max(int(min(self.client_max_jobs, self.client_cores())),
                       1)
        next_seed = 0
        in_flight = {}
        results = {}
        harvested = 0  # next submission id to account
        #: simulation budget charges UNIQUE dispatched batches, not
        #: attempts — a transiently-failed batch that is resubmitted and
        #: succeeds counts once (through the Sample), and only a batch
        #: written off for good charges failed_evals
        failed_evals = 0
        seed_retries = {}
        consecutive_failures = 0
        bar = None
        if getattr(self, "show_progress", False):
            from ..utils.progress import ProgressBar
            bar = ProgressBar(n, desc="sampling")
        try:
            while True:
                # submission-order accounting (reference eps_mixin.py:62-81)
                while harvested in results:
                    rr = results.pop(harvested)
                    if rr is not None:  # None = failed batch, nothing to add
                        sample.append_round(rr)
                    harvested += 1
                if bar is not None:
                    bar.update(min(sample.n_accepted, n))
                if sample.n_accepted >= n or (
                        sample.nr_evaluations + failed_evals >= max_eval
                        and sample.n_accepted < n):
                    break
                while len(in_flight) < max_jobs:
                    fut = self._submit(eval_batch, next_seed)
                    in_flight[fut] = next_seed
                    next_seed += 1
                done = self._wait_any(list(in_flight))
                try:
                    seed, rr = done.result()
                    consecutive_failures = 0
                except Exception as err:  # model error or dead worker
                    seed = in_flight.pop(done)
                    consecutive_failures += 1
                    if consecutive_failures > self.max_consecutive_failures:
                        raise RuntimeError(
                            f"{consecutive_failures} consecutive batch "
                            "failures — model or cluster is persistently "
                            "broken") from err
                    if self._is_broken_backend(err):
                        # in-flight futures all died with the backend —
                        # drop them and resubmit their seeds (the dying
                        # one included: its simulations never ran, so a
                        # retry is an attempt, not a new batch — no
                        # failed_evals charge) after recovery
                        if not self._recover():
                            raise
                        lost = sorted(set(in_flight.values()) | {seed})
                        in_flight = {}
                        for s in lost:
                            in_flight[self._submit(eval_batch, s)] = s
                        logger.warning(
                            "backend died under batch %d (%s: %s) — "
                            "rebuilt, %d batches resubmitted", seed,
                            type(err).__name__, err, len(lost))
                        continue
                    retries = seed_retries.get(seed, 0)
                    if (_retry.is_transient(err)
                            and retries < self.max_transient_retries):
                        # transient infrastructure failure: same batch,
                        # new attempt — unique dispatched batches are
                        # charged once, attempts are not
                        seed_retries[seed] = retries + 1
                        in_flight[self._submit(eval_batch, seed)] = seed
                        logger.warning(
                            "batch %d failed transiently (%s: %s) — "
                            "resubmitted (attempt %d/%d)", seed,
                            type(err).__name__, err, retries + 1,
                            self.max_transient_retries)
                        continue
                    failed_evals += B
                    logger.warning(
                        "batch %d failed (%s: %s) — discarded, continuing "
                        "with fresh work", seed, type(err).__name__, err)
                    results[seed] = None
                    continue
                del in_flight[done]
                results[seed] = rr
        finally:
            if bar is not None:
                bar.finish()
            for fut in in_flight:
                self._cancel(fut)
        self.nr_evaluations_ = sample.nr_evaluations + failed_evals
        return sample

    @staticmethod
    def _is_broken_backend(err: Exception) -> bool:
        """Whether the error means the whole backend died (vs one batch)."""
        from concurrent.futures import BrokenExecutor
        return isinstance(err, BrokenExecutor)
