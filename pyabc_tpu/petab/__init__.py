"""PEtab bridge (parity: pyabc/petab/)."""

from .base import PetabImporter
from .ode import LikelihoodODEModel, ODEPetabImporter
from .problem import PetabProblem, PetabSBMLModel, SBMLPetabImporter
from .sbml import SBMLModel, parse_sbml

__all__ = ["PetabImporter", "ODEPetabImporter", "LikelihoodODEModel",
           "PetabProblem", "PetabSBMLModel", "SBMLPetabImporter",
           "SBMLModel", "parse_sbml"]
