"""Deterministic ODE models via fixed-step RK4 under ``lax.scan``.

The reference integrates ODEs through the AMICI bridge
(pyabc/petab/amici.py:26-170); here ODE right-hand sides are plain JAX
functions batched over the population — the petab bridge
(pyabc_tpu/petab) builds on this model class.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..model import Model

Array = jnp.ndarray


class ODEModel(Model):
    """Fixed-step RK4 integrator for ``dy/dt = rhs(y, theta)``.

    ``rhs(y[N, S], theta[N, D]) -> [N, S]`` must be batched; ``observe``
    maps the trajectory ``[T, N, S]`` to a sum-stat dict.  Optional
    ``noise_scale`` adds measurement noise (making the model stochastic,
    as ABC expects).
    """

    #: the low-fidelity variant keeps the exact summary-stat layout
    #: (fidelity-cascade contract, docs/fidelity.md)
    screen_stats_compatible = True

    def __init__(self, rhs: Callable, y0, t_max: float, n_steps: int,
                 observe: Optional[Callable] = None,
                 obs_idx=None, noise_scale: float = 0.0,
                 name: str = "ode"):
        super().__init__(name)
        self.rhs = rhs
        self.y0 = jnp.asarray(y0, dtype=jnp.float32)
        self.t_max = float(t_max)
        self.n_steps = int(n_steps)
        self.dt = self.t_max / self.n_steps
        self.observe = observe
        self.obs_idx = (jnp.asarray(obs_idx, dtype=jnp.int32)
                        if obs_idx is not None
                        else jnp.arange(self.n_steps, dtype=jnp.int32))
        self.noise_scale = float(noise_scale)

    def sample(self, key, theta: Array) -> Dict[str, Array]:
        n = theta.shape[0]
        y_init = jnp.broadcast_to(self.y0, (n,) + self.y0.shape)
        dt = self.dt

        def step(y, _):
            k1 = self.rhs(y, theta)
            k2 = self.rhs(y + 0.5 * dt * k1, theta)
            k3 = self.rhs(y + 0.5 * dt * k2, theta)
            k4 = self.rhs(y + dt * k3, theta)
            y = y + dt / 6.0 * (k1 + 2 * k2 + 2 * k3 + k4)
            return y, y

        _, traj = lax.scan(step, y_init, None, length=self.n_steps)
        obs = traj[self.obs_idx]                        # [T_obs, N, S]
        if self.noise_scale > 0:
            obs = obs + self.noise_scale * jax.random.normal(key, obs.shape)
        if self.observe is not None:
            return self.observe(obs)
        # default: one stat per state dimension, [N, T_obs]
        return {f"y{i}": jnp.moveaxis(obs[..., i], 0, -1)
                for i in range(obs.shape[-1])}

    def low_fidelity(self) -> "ODEModel":
        """4x coarser RK4 grid over the same horizon.  The observation
        indices are rescaled onto the coarse grid with their COUNT
        preserved, so the trajectory slice — and therefore every
        downstream summary statistic — keeps its exact shape."""
        import numpy as np
        coarse = max(self.n_steps // 4, 1)
        idx = np.asarray(self.obs_idx, dtype=np.float64)
        scaled = np.clip(
            np.round(idx * coarse / self.n_steps), 0, coarse - 1
        ).astype(np.int32)
        return ODEModel(rhs=self.rhs, y0=self.y0, t_max=self.t_max,
                        n_steps=coarse, observe=self.observe,
                        obs_idx=scaled, noise_scale=self.noise_scale,
                        name=self.name + "_lofi")
