"""Transition tests (parity: reference test/base/test_transition.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from scipy import stats as ss

import pyabc_tpu as pt
from pyabc_tpu.transition import (
    DiscreteRandomWalkTransition,
    GridSearchCV,
    LocalTransition,
    MultivariateNormalTransition,
    NotFittedError,
    smart_cov,
)


@pytest.fixture(params=["mvn", "local", "walk"])
def transition(request):
    return {
        "mvn": MultivariateNormalTransition(),
        "local": LocalTransition(k=20),
        "walk": DiscreteRandomWalkTransition(),
    }[request.param]


def _fit_data(key, n=200, d=2):
    theta = jax.random.normal(key, (n, d)) * jnp.asarray([1.0, 2.0]) + 1.0
    w = jnp.ones(n) / n
    return theta, w


def test_not_fitted_raises(transition, key):
    with pytest.raises(NotFittedError):
        transition.rvs(key)
    with pytest.raises(NotFittedError):
        transition.pdf(jnp.zeros(2))


def test_rvs_shape_and_pdf_positive(transition, key):
    theta, w = _fit_data(key)
    if isinstance(transition, DiscreteRandomWalkTransition):
        theta = jnp.round(theta)
    transition.fit(theta, w)
    k1, k2 = jax.random.split(key)
    draws = transition.rvs(k1, 50)
    assert draws.shape == (50, 2)
    pdfs = transition.pdf(draws)
    assert np.all(np.asarray(pdfs) > 0)
    single = transition.rvs(k2)
    assert single.shape == (2,)


def test_mvn_pdf_matches_manual_kde(key):
    theta, w = _fit_data(key, n=50)
    tr = MultivariateNormalTransition()
    tr.fit(theta, w)
    params = tr.get_params()
    cov = np.asarray(params["chol"]) @ np.asarray(params["chol"]).T
    x = np.asarray([[0.0, 0.0], [1.0, 1.0]])
    manual = np.zeros(2)
    th = np.asarray(theta)
    wn = np.asarray(tr.w)
    for i in range(len(th)):
        manual += wn[i] * ss.multivariate_normal.pdf(x, th[i], cov)
    # the MXU matmul formulation of the Mahalanobis term trades ~0.5%
    # f32 accuracy for streaming speed (ops/kde.py) — harmless vs the
    # Monte Carlo noise ABC operates under
    ours = np.asarray(tr.pdf(jnp.asarray(x, dtype=jnp.float32)))
    assert np.allclose(ours, manual, rtol=1e-2)


def test_mvn_pdf_chunking_consistent(key):
    """Chunked logsumexp must equal the direct path."""
    theta, w = _fit_data(key, n=100)
    tr = MultivariateNormalTransition()
    tr.fit(theta, w)
    x = jax.random.normal(key, (300, 2))
    direct = tr.log_pdf_from_params(x, tr.get_params(), chunk=1024)
    chunked = tr.log_pdf_from_params(x, tr.get_params(), chunk=64)
    assert np.allclose(np.asarray(direct), np.asarray(chunked), atol=1e-4)


def test_mvn_rvs_distribution(key):
    """Samples should be support-resamples + bandwidth noise: mean matches."""
    theta, w = _fit_data(key, n=500)
    tr = MultivariateNormalTransition()
    tr.fit(theta, w)
    draws = np.asarray(tr.rvs(key, 20000))
    assert np.allclose(draws.mean(0), np.asarray(theta).mean(0), atol=0.1)


def test_weighted_fit_shifts_proposal(key):
    theta = jnp.asarray([[0.0], [10.0]])
    w = jnp.asarray([0.95, 0.05])
    tr = MultivariateNormalTransition()
    tr.fit(theta, w)
    draws = np.asarray(tr.rvs(key, 2000))
    frac_near_zero = (np.abs(draws[:, 0]) < 5.0).mean()
    assert frac_near_zero > 0.85


def test_discrete_random_walk_stays_integer(key):
    theta = jnp.asarray([[0.0], [1.0], [2.0]])
    tr = DiscreteRandomWalkTransition(n_steps=1, p_stay=0.5)
    tr.fit(theta, jnp.ones(3) / 3)
    draws = np.asarray(tr.rvs(key, 500))
    assert np.allclose(draws, np.round(draws))
    # pmf sums to one over the reachable grid
    grid = jnp.arange(-2.0, 5.0)[:, None]
    pmf = np.asarray(tr.pdf(grid))
    assert pmf.sum() == pytest.approx(1.0, abs=1e-4)


def test_smart_cov_matches_numpy(key):
    theta, w = _fit_data(key, n=300)
    cov = np.asarray(smart_cov(theta, w / jnp.sum(w)))
    expected = np.cov(np.asarray(theta), rowvar=False, bias=True)
    assert np.allclose(cov, expected, atol=1e-3)


def test_mean_cv_decreases_with_n(key):
    tr_small = MultivariateNormalTransition()
    tr_big = MultivariateNormalTransition()
    k1, k2 = jax.random.split(key)
    theta_s, w_s = _fit_data(k1, n=30)
    theta_b, w_b = _fit_data(k2, n=500)
    tr_small.fit(theta_s, w_s)
    tr_big.fit(theta_b, w_b)
    cv_small = tr_small.mean_cv(k1, n_bootstrap=5)
    cv_big = tr_big.mean_cv(k2, n_bootstrap=5)
    assert cv_big < cv_small


def test_grid_search_cv(key):
    theta, w = _fit_data(key, n=100)
    gs = GridSearchCV(param_grid={"scaling": [0.5, 1.0]}, n_bootstrap=2)
    gs.fit(theta, w)
    assert gs.best_params_["scaling"] in (0.5, 1.0)
    assert gs.rvs(key, 10).shape == (10, 2)
    rvs_fn, pdf_fn = gs.static_fns()
    assert rvs_fn is MultivariateNormalTransition.rvs_from_params


def test_local_transition_e2e_abcsmc(db_path):
    """LocalTransition drives the FULL compiled pipeline (fused rounds,
    deferred proposal density, finalize correction) — not just the
    eager fit/rvs/pdf surface."""
    from pyabc_tpu.models import make_two_gaussians_problem

    models, priors, distance, observed, posterior_fn = \
        make_two_gaussians_problem()
    abc = pt.ABCSMC(models, priors, distance,
                    population_size=400,
                    transitions=[LocalTransition(k=25) for _ in models],
                    sampler=pt.VectorizedSampler(),
                    seed=5)
    abc.new(db_path, observed)
    h = abc.run(max_nr_populations=3)
    probs = h.get_model_probabilities(h.max_t)
    assert abs(float(probs.get(1, 0.0)) - posterior_fn(1.0)) < 0.25


def test_discrete_random_walk_e2e_abcsmc(db_path):
    """DiscreteRandomWalkTransition over an integer parameter runs the
    full pipeline and concentrates on the true integer."""

    def model(key, theta):
        lam = theta[:, 0]
        return {"y": lam + 0.3 * jax.random.normal(key, lam.shape)}

    abc = pt.ABCSMC(
        models=pt.SimpleModel(model),
        parameter_priors=pt.Distribution(k=pt.RV("randint", 0, 10)),
        distance_function=pt.PNormDistance(p=2),
        population_size=400,
        transitions=DiscreteRandomWalkTransition(),
        sampler=pt.VectorizedSampler(),
        seed=6)
    abc.new(db_path, {"y": 4.0})
    h = abc.run(max_nr_populations=4)
    df, w = h.get_distribution()
    draws = df.iloc[:, 0].to_numpy()
    assert np.allclose(draws, np.round(draws))  # stays on the lattice
    mode = draws[np.argmax(w)]
    mean = float(np.sum(draws * w))
    assert abs(mean - 4.0) < 1.0, (mode, mean)


def test_grid_search_cv_e2e_abcsmc(db_path):
    """GridSearchCV-wrapped transition delegates its static kernels to the
    base estimator inside the compiled round."""
    from pyabc_tpu.models import make_two_gaussians_problem

    models, priors, distance, observed, posterior_fn = \
        make_two_gaussians_problem()
    abc = pt.ABCSMC(
        models, priors, distance,
        population_size=300,
        transitions=[pt.GridSearchCV(
            pt.MultivariateNormalTransition(),
            {"scaling": [0.5, 1.0, 2.0]}) for _ in models],
        sampler=pt.VectorizedSampler(),
        seed=7)
    abc.new(db_path, observed)
    h = abc.run(max_nr_populations=3)
    probs = h.get_model_probabilities(h.max_t)
    assert abs(float(probs.get(1, 0.0)) - posterior_fn(1.0)) < 0.3


def test_aggregated_transition_e2e_abcsmc(db_path):
    """AggregatedTransition composes sub-transitions inside the COMPILED
    round (static_fns composition): a 2-parameter problem split into two
    per-column sub-transitions infers both parameters."""
    def model(key, theta):
        n = theta.shape[0]
        k1, k2 = jax.random.split(key)
        return {"a": theta[:, 0] + 0.1 * jax.random.normal(k1, (n,)),
                "b": theta[:, 1] + 0.1 * jax.random.normal(k2, (n,))}

    agg = pt.AggregatedTransition({
        (0, 1): MultivariateNormalTransition(),
        (1, 2): MultivariateNormalTransition(scaling=0.5),
    })
    abc = pt.ABCSMC(
        models=pt.SimpleModel(model),
        parameter_priors=pt.Distribution(
            mu_a=pt.RV("uniform", -1.0, 2.0),
            mu_b=pt.RV("uniform", -1.0, 2.0)),
        distance_function=pt.PNormDistance(p=2),
        population_size=400,
        transitions=agg,
        sampler=pt.VectorizedSampler(),
        seed=8)
    abc.new(db_path, {"a": 0.3, "b": 0.7})
    h = abc.run(max_nr_populations=4)
    df, w = h.get_distribution()
    mean_a = float(np.sum(df["mu_a"].to_numpy() * w))
    mean_b = float(np.sum(df["mu_b"].to_numpy() * w))
    assert abs(mean_a - 0.3) < 0.15, mean_a
    assert abs(mean_b - 0.7) < 0.15, mean_b


def test_aggregated_transition_order_and_coverage():
    """Insertion order of the mapping must not matter (iteration is
    always ascending), and gapped/overlapping mappings raise instead of
    silently misaligning columns."""
    # reversed insertion order: the composed kernels and the eager
    # surface must still place slice (0,1) in column 0
    agg = pt.AggregatedTransition({
        (1, 2): MultivariateNormalTransition(),
        (0, 1): MultivariateNormalTransition(),
    })
    theta = jnp.asarray(
        np.column_stack([np.full(64, 5.0), np.full(64, -5.0)]),
        dtype=jnp.float32)
    agg.fit(theta, jnp.ones(64) / 64)
    draws = np.asarray(agg.rvs(jax.random.PRNGKey(0), 256))
    assert abs(draws[:, 0].mean() - 5.0) < 0.5
    assert abs(draws[:, 1].mean() + 5.0) < 0.5
    rvs_static, _ = agg.static_fns()
    params = agg.pad_params(agg.get_params(), 64)
    draws_s = np.asarray(rvs_static(jax.random.PRNGKey(1), params, 256))
    assert abs(draws_s[:, 0].mean() - 5.0) < 0.5
    assert abs(draws_s[:, 1].mean() + 5.0) < 0.5

    with pytest.raises(ValueError, match="contiguously"):
        pt.AggregatedTransition({(0, 1): MultivariateNormalTransition(),
                                 (2, 3): MultivariateNormalTransition()})
    with pytest.raises(ValueError, match="empty"):
        pt.AggregatedTransition({(1, 1): MultivariateNormalTransition()})


def test_mvn_compressed_pdf_support(key):
    """Above the compression threshold a 1-D fit evaluates its pdf
    against the grid-compressed support (c_* params) and matches the
    exact pairwise evaluation to ~1e-3 in log density."""
    from pyabc_tpu.ops.kde import weighted_kde_logpdf_auto

    n = (1 << 14) + 7  # just over the threshold, non-pow2
    rng = np.random.default_rng(0)
    # bimodal, uneven weights: stresses per-cell centroids and masses
    theta = np.concatenate([rng.normal(-2.0, 0.5, n // 2),
                            rng.normal(1.0, 0.2, n - n // 2)])
    w = rng.random(n) + 1e-3
    tr = MultivariateNormalTransition()
    tr.fit(theta[:, None].astype(np.float32), w.astype(np.float32))
    params = tr.get_params()
    assert "c_support" in params
    g = params["c_support"].shape[0]
    assert g == tr._grid_g and g & (g - 1) == 0  # pow2 grid
    # compressed pdf (the production path)
    x = np.linspace(-4.0, 2.5, 512, dtype=np.float32)[:, None]
    lp_c = np.asarray(tr.log_pdf(x))
    # exact pairwise over the full support
    lp_e = np.asarray(weighted_kde_logpdf_auto(
        jnp.asarray(x), jnp.asarray(params["support"]),
        jnp.asarray(params["log_w"]), jnp.asarray(params["chol"]),
        jnp.asarray(params["log_norm"])))
    assert np.allclose(lp_c, lp_e, atol=5e-3)
    # mass conservation: total compressed weight == total weight
    np.testing.assert_allclose(
        np.exp(params["c_log_w"]).sum(), 1.0, rtol=1e-5)
    # pad_params passes the grid arrays through un-padded
    padded = tr.pad_params(params, 1 << 15)
    assert padded["c_support"].shape[0] == g
    assert padded["support"].shape[0] == 1 << 15


def test_mvn_compression_grid_hysteresis(key):
    """Refits with drifting data keep the grid shape (pytree stability:
    a changed grid size would recompile the round program)."""
    rng = np.random.default_rng(1)
    tr = MultivariateNormalTransition()
    n = 1 << 14
    gs = []
    for scale in (1.0, 0.9, 1.1, 1.05):
        theta = rng.normal(0.0, scale, n).astype(np.float32)[:, None]
        tr.fit(theta, np.ones(n, dtype=np.float32))
        assert tr._compressed is not None
        gs.append(tr._compressed[0].shape[0])
    assert len(set(gs)) == 1


def test_mvn_small_fit_not_compressed(key):
    """Below the threshold the params stay exact (no c_* keys) so small
    problems keep bit-identical semantics."""
    theta, w = _fit_data(key, n=256, d=2)
    tr = MultivariateNormalTransition()
    tr.fit(np.asarray(theta), np.asarray(w))
    assert "c_support" not in tr.get_params()
