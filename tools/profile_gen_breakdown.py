"""Per-component breakdown of north-star generations: step vs finalize
vs host choreography, with forced syncs so each piece is billed honestly.

Run on the real TPU:  python tools/profile_gen_breakdown.py [pop_log2]
"""
import os
import sys
import time
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

import jax
jax.config.update("jax_compilation_cache_dir", "/tmp/pyabc_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
import jax.numpy as jnp


def _sync(out):
    leaves = [l for l in jax.tree_util.tree_leaves(out)
              if hasattr(l, "ravel")]
    return float(sum(jnp.sum(jnp.asarray(l, jnp.float32).ravel()[:1])
                     for l in leaves[:2]))


TIMES = defaultdict(list)


def _wrap(name, fn, sync=True):
    def wrapped(*a, **kw):
        t0 = time.perf_counter()
        out = fn(*a, **kw)
        if sync:
            _sync(out)
        TIMES[name].append(time.perf_counter() - t0)
        return out
    return wrapped


def main():
    problem = sys.argv[1] if len(sys.argv) > 1 else "northstar"
    pop = int(sys.argv[2]) if len(sys.argv) > 2 else 1_000_000

    import pyabc_tpu as pt
    from pyabc_tpu.models import make_two_gaussians_problem
    from pyabc_tpu.sampler.vectorized import VectorizedSampler

    orig = VectorizedSampler._build_stateful

    def patched(self, *a, **kw):
        (start, step, finalize, harvest, reset,
         step_finalize) = orig(self, *a, **kw)
        return (_wrap("start", start), _wrap("step", step),
                _wrap("finalize", finalize), _wrap("harvest", harvest),
                _wrap("reset_nosync", reset, sync=False),
                _wrap("step_finalize", step_finalize))

    VectorizedSampler._build_stateful = patched

    # host-side pieces.  The d2h transfer lives in fetch_to_host (the
    # ingest itself only widens host arrays since the f16-wire change);
    # patch BOTH the defining module and the vectorized module's
    # from-import binding, or the wrapper never runs.
    import pyabc_tpu.sampler.base as sbase
    import pyabc_tpu.sampler.vectorized as vec_mod
    wrapped_fetch = _wrap("d2h_fetch", sbase.fetch_to_host, sync=False)
    sbase.fetch_to_host = wrapped_fetch
    vec_mod.fetch_to_host = wrapped_fetch
    sbase.Sample.append_device_batch = _wrap(
        "ingest_widen", sbase.Sample.append_device_batch, sync=False)
    orig_dput = jax.device_put
    jax.device_put = _wrap("device_put", orig_dput, sync=False)
    import pyabc_tpu.storage.history as hist_mod
    hist_mod.History.append_population = _wrap(
        "db_append", hist_mod.History.append_population, sync=False)
    import pyabc_tpu.smc as smc_mod0
    smc_mod0.ABCSMC._fit_transitions = _wrap(
        "fit_transitions", smc_mod0.ABCSMC._fit_transitions, sync=False)

    if problem == "northstar":
        models, priors, distance, observed, _ = \
            make_two_gaussians_problem()
        abc = pt.ABCSMC(
            models, priors, distance,
            population_size=pop,
            eps=pt.ConstantEpsilon(0.2),
            sampler=pt.VectorizedSampler(max_batch_size=1 << 19,
                                         max_rounds_per_call=16),
            seed=0)
        abc.new("sqlite://", observed)
    elif problem == "petab":
        import pandas as pd

        from pyabc_tpu.petab import ODEPetabImporter
        par_df = pd.DataFrame({
            "parameterId": ["k"], "parameterScale": ["lin"],
            "lowerBound": [0.01], "upperBound": [3.0], "estimate": [1],
            "objectivePriorType": ["uniform"],
            "objectivePriorParameters": ["0.01;3.0"],
        }).set_index("parameterId")
        t_max, n_steps = 2.0, 20
        obs_idx = np.asarray([4, 9, 14, 19])
        times = (obs_idx + 1) * (t_max / n_steps)
        rng = np.random.default_rng(0)
        data = np.exp(-0.7 * times) + 0.05 * rng.normal(size=times.shape)
        importer = ODEPetabImporter(
            par_df, rhs=lambda y, th: -th[:, 0:1] * y, y0=[1.0],
            t_max=t_max, n_steps=n_steps, obs_idx=obs_idx,
            measurements={"y0": data}, sigma=0.05)
        abc = pt.ABCSMC(
            models=importer.create_model(),
            parameter_priors=importer.create_prior(),
            distance_function=importer.create_kernel(),
            population_size=pop,
            eps=pt.Temperature(aggregate_fun=max),
            acceptor=pt.StochasticAcceptor(),
            sampler=pt.VectorizedSampler(min_batch_size=1 << 18,
                                         max_batch_size=1 << 18),
            seed=0)
        abc.new("sqlite://", importer.get_observed())
    else:
        from pyabc_tpu.models import (make_lotka_volterra_problem,
                                      make_sir_problem)
        maker = {"lv": make_lotka_volterra_problem,
                 "sir": make_sir_problem}[problem]
        models, priors, distance, observed = maker()
        abc = pt.ABCSMC(
            models, priors, distance,
            population_size=pop,
            sampler=pt.VectorizedSampler(min_batch_size=1 << 19,
                                         max_batch_size=1 << 19),
            seed=0)
        abc.new("sqlite://", observed)

    import pyabc_tpu.sampler.base as sbase2
    sbase2.Sample.append_record_batch = _wrap(
        "record_ingest", sbase2.Sample.append_record_batch, sync=False)
    abc.eps.update = _wrap("eps_update", abc.eps.update, sync=False)
    abc.distance_function.update = _wrap(
        "distance_update", abc.distance_function.update, sync=False)

    gen_t0 = time.perf_counter()
    gen_marks = []

    import pyabc_tpu.smc as smc_mod
    orig_prep = smc_mod.ABCSMC._prepare_next_iteration

    def prep(self, *a, **kw):
        t0 = time.perf_counter()
        out = orig_prep(self, *a, **kw)
        TIMES["prepare_next"].append(time.perf_counter() - t0)
        gen_marks.append(time.perf_counter() - gen_t0)
        return out

    smc_mod.ABCSMC._prepare_next_iteration = prep

    abc.run(max_nr_populations=6)

    print(f"pop={pop}")
    print("generation wall marks:",
          [round(m, 2) for m in gen_marks],
          "deltas:", [round(b - a, 2) for a, b in
                      zip(gen_marks, gen_marks[1:])])
    for name, ts in TIMES.items():
        print(f"{name:14s} n={len(ts):3d} total={sum(ts):7.2f}s "
              f"last5={[round(t, 3) for t in ts[-5:]]}")
    for t in sorted(abc.generation_transfer):
        tr = abc.generation_transfer[t]
        print(f"gen {t}: wall={abc.generation_wall_clock.get(t, 0):.2f}s "
              f"d2h={tr['d2h_bytes'] / 1e6:.2f}MB/{tr['d2h_s']:.2f}s "
              f"({tr['d2h_calls']} calls) h2d={tr['h2d_bytes'] / 1e6:.2f}MB")
    # transition state
    for m, tr in enumerate(abc.transitions):
        comp = getattr(tr, "_compressed", None)
        print(f"model {m}: support={tr.theta.shape} "
              f"grid={None if comp is None else comp[0].shape[0]} "
              f"pad_buckets={abc._pad_buckets}")


if __name__ == "__main__":
    main()
