"""Rule ``event-discipline``: every queue state transition in the
serving/scheduling tier emits (or delegates to something that emits) a
lifecycle trace event.

The study trace (serve/tracing.py, docs/observability.md "Tracing a
study") is only trustworthy if it is COMPLETE: a transition method
that moves a ticket between queue states without appending its event
leaves a hole in the critical path — ``fold_phases`` silently charges
the missing span to the neighboring phase and every latency
attribution downstream (tombstone breakdown, SLO burn ledger, Chrome
export) is wrong in a way no test of the emitting paths can catch.
The contract is therefore structural: a function named after a queue
transition (``submit`` / ``claim`` / ``complete`` / ``fail`` /
``requeue`` / ``requeue_worker`` / ``quarantine`` / ``_move``) defined
under ``pyabc_tpu/serve/`` or ``pyabc_tpu/sched/`` must do one of:

- call ``.emit(...)`` somewhere in its body (the transition logs
  itself), or
- call another transition method (delegation: ``complete`` →
  ``_move`` — the callee owns the event), or
- carry ``# event-ok`` on its ``def`` line — for transitions whose
  event is intentionally owned elsewhere (e.g. a caller that batches
  emissions), mirroring ``# claim-ok`` / ``# wire-ok``.

The generic ``# graftlint: allow(event-discipline)`` works as
everywhere else.  Scope matches ``claim-discipline``: only the two
packages that own the queue's state machine; tests and tools move
tickets without ceremony.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from ..core import Finding, Rule, register

#: queue state-transition method names the rule binds to
TRANSITIONS = frozenset({
    "submit", "claim", "complete", "fail", "requeue",
    "requeue_worker", "quarantine", "_move"})

EVENT_OK = "# event-ok"

#: package-relative directory prefixes the rule applies to
SCOPES = ("serve/", "sched/")


def _call_attr(call: ast.Call) -> Optional[str]:
    if isinstance(call.func, ast.Attribute):
        return call.func.attr
    if isinstance(call.func, ast.Name):
        return call.func.id
    return None


def _satisfied(func: ast.AST) -> bool:
    """True when ``func`` emits a trace event or delegates to another
    transition method (which then owns the emission)."""
    for node in ast.walk(func):
        if not isinstance(node, ast.Call):
            continue
        attr = _call_attr(node)
        if attr == "emit":
            return True
        if attr in TRANSITIONS and attr != func.name:
            return True
    return False


def check(files) -> List[tuple]:
    """``files`` is an iterable of (rel, SourceFile) pairs scoped to
    serve/ + sched/; returns ``[(rel, lineno, message), ...]``."""
    violations = []
    for rel, sf in files:
        tree = sf.tree
        if tree is None:
            continue
        for func in ast.walk(tree):
            if not isinstance(func, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if func.name not in TRANSITIONS:
                continue
            if EVENT_OK in sf.line(func.lineno):
                continue
            if _satisfied(func):
                continue
            violations.append((
                rel, func.lineno,
                f"transition `{func.name}` neither emits a lifecycle "
                "event nor delegates to a transition that does — the "
                "study trace loses this state change and phase "
                "attribution silently absorbs the gap (call "
                ".emit(...), delegate, or mark `# event-ok`)"))
    violations.sort()
    return violations


@register
class EventDisciplineRule(Rule):
    id = "event-discipline"
    description = ("queue transitions in serve/ and sched/ emit their "
                   "lifecycle trace event (or delegate to a "
                   "transition that does)")

    def run(self, tree):
        prefix = tree.package_rel_prefix()
        pairs = [(sf.rel, sf) for sf in tree.package_files()
                 if sf.rel.startswith(SCOPES)]
        return [Finding(self.id, f"{prefix}/{rel}", lineno, msg)
                for rel, lineno, msg in check(pairs)]
