"""Distribution / RV parity tests (reference test/base/test_random_variables... )."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from scipy import stats as ss

import pyabc_tpu as pt
from pyabc_tpu.random_variables import (
    Beta, Cauchy, Expon, Gamma, Laplace, LogNorm, Norm, Poisson, Randint,
    TruncatedRV, Uniform,
)


@pytest.mark.parametrize("rv,scipy_rv", [
    (Norm(1.0, 2.0), ss.norm(1.0, 2.0)),
    (Uniform(-1.0, 3.0), ss.uniform(-1.0, 3.0)),
    (Expon(0.0, 2.0), ss.expon(0.0, 2.0)),
    (Laplace(0.5, 1.5), ss.laplace(0.5, 1.5)),
    (Cauchy(0.0, 1.0), ss.cauchy(0.0, 1.0)),
    (Gamma(2.0, 1.5), ss.gamma(2.0, scale=1.5)),
    (Beta(2.0, 3.0), ss.beta(2.0, 3.0)),
    (LogNorm(0.5, 2.0), ss.lognorm(0.5, scale=2.0)),
])
def test_log_pdf_matches_scipy(rv, scipy_rv):
    x = np.asarray(scipy_rv.rvs(size=50, random_state=1), dtype=np.float32)
    ours = np.asarray(rv.log_pdf(jnp.asarray(x)))
    theirs = scipy_rv.logpdf(x)
    assert np.allclose(ours, theirs, atol=1e-3, rtol=1e-3)


@pytest.mark.parametrize("rv,scipy_rv", [
    (Norm(1.0, 2.0), ss.norm(1.0, 2.0)),
    (Uniform(-1.0, 3.0), ss.uniform(-1.0, 3.0)),
    (Gamma(2.0, 1.5), ss.gamma(2.0, scale=1.5)),
])
def test_sample_moments(key, rv, scipy_rv):
    x = np.asarray(rv.sample(key, (20000,)))
    assert abs(x.mean() - scipy_rv.mean()) < 0.1 * max(scipy_rv.std(), 1)
    assert abs(x.std() - scipy_rv.std()) < 0.1 * scipy_rv.std()


def test_rv_factory():
    assert isinstance(pt.RV("norm", 0, 1), Norm)
    with pytest.raises(ValueError):
        pt.RV("nope")


def test_distribution_joint(key):
    dist = pt.Distribution(a=pt.RV("norm", 0, 1), b=pt.RV("uniform", 0, 2))
    theta = dist.rvs_array(key, 1000)
    assert theta.shape == (1000, 2)
    lp = dist.log_pdf_array(theta)
    expected = (ss.norm(0, 1).logpdf(np.asarray(theta[:, 0]))
                + ss.uniform(0, 2).logpdf(np.asarray(theta[:, 1])))
    assert np.allclose(np.asarray(lp), expected, atol=1e-3)


def test_distribution_scalar_api(key):
    dist = pt.Distribution(a=pt.RV("norm", 0, 1))
    p = dist.rvs(key)
    assert "a" in p
    assert dist.pdf({"a": 0.0}) == pytest.approx(ss.norm.pdf(0.0), rel=1e-3)


def test_truncated_rv(key):
    rv = TruncatedRV(Norm(0.0, 1.0), lower=1.0)
    x = np.asarray(rv.sample(key, (5000,)))
    assert x.min() >= 1.0
    # renormalized density integrates the tail correctly
    z = 1.0 - ss.norm.cdf(1.0)
    assert float(rv.log_pdf(jnp.asarray(1.5))) == pytest.approx(
        ss.norm.logpdf(1.5) - np.log(z), abs=1e-3)
    assert float(rv.log_pdf(jnp.asarray(0.5))) == -np.inf


def test_model_perturbation_kernel(key):
    kern = pt.ModelPerturbationKernel(3, probability_to_stay=0.7)
    m = jnp.zeros(20000, dtype=jnp.int32)
    m_new = np.asarray(kern.rvs(key, m))
    stay = (m_new == 0).mean()
    assert abs(stay - 0.7) < 0.02
    assert set(np.unique(m_new)) <= {0, 1, 2}
    assert float(kern.pmf(1, 0)) == pytest.approx(0.15, abs=1e-4)
    assert float(kern.pmf(0, 0)) == pytest.approx(0.7, abs=1e-4)


def test_discrete_rvs(key):
    r = Randint(0, 5)
    x = np.asarray(r.sample(key, (1000,)))
    assert set(np.unique(x)) <= set(range(5))
    assert float(r.pmf(jnp.asarray(2.0))) == pytest.approx(0.2, abs=1e-4)
    p = Poisson(3.0)
    assert float(p.log_pdf(jnp.asarray(2.0))) == pytest.approx(
        ss.poisson.logpmf(2, 3.0), abs=2e-3)


@pytest.mark.parametrize("rv,scipy_rv", [
    (pt.RV("t", 3.0), ss.t(3.0)),
    (pt.RV("t", 4.0, 1.0, 2.0), ss.t(4.0, 1.0, 2.0)),
    (pt.RV("chi2", 5.0), ss.chi2(5.0)),
    (pt.RV("weibull_min", 1.8, 0.0, 2.0), ss.weibull_min(1.8, 0.0, 2.0)),
])
def test_new_native_continuous_rvs(key, rv, scipy_rv):
    x = np.asarray(scipy_rv.rvs(size=50, random_state=2), dtype=np.float32)
    assert np.allclose(np.asarray(rv.log_pdf(jnp.asarray(x))),
                       scipy_rv.logpdf(x), atol=2e-3, rtol=1e-3)
    assert np.allclose(np.asarray(rv.cdf(jnp.asarray(x))),
                       scipy_rv.cdf(x), atol=2e-3)
    draws = np.asarray(rv.sample(key, (20000,)))
    assert abs(np.median(draws) - scipy_rv.median()) \
        < 0.1 * max(scipy_rv.std(), 1.0)


@pytest.mark.parametrize("rv,scipy_rv", [
    (pt.RV("binom", 12, 0.3), ss.binom(12, 0.3)),
    (pt.RV("nbinom", 5, 0.4), ss.nbinom(5, 0.4)),
])
def test_new_native_discrete_rvs(key, rv, scipy_rv):
    assert rv.discrete
    ks = np.arange(0, 15, dtype=np.float32)
    assert np.allclose(np.asarray(rv.log_pdf(jnp.asarray(ks))),
                       scipy_rv.logpmf(ks), atol=2e-3, rtol=1e-3)
    assert np.allclose(np.asarray(rv.cdf(jnp.asarray(ks))),
                       scipy_rv.cdf(ks), atol=2e-3)
    draws = np.asarray(rv.sample(key, (20000,)))
    assert abs(draws.mean() - scipy_rv.mean()) < 0.1 * scipy_rv.std()
    assert np.all(draws == np.round(draws))


def test_scipy_rv_fallback(key):
    """Any scipy.stats name resolves (reference random_variables.py:147-169);
    the host-callback path works eagerly AND under jit."""
    from pyabc_tpu.random_variables import ScipyRV

    rv = pt.RV("skewnorm", 4.0)
    assert isinstance(rv, ScipyRV)
    ref = ss.skewnorm(4.0)
    x = np.asarray(ref.rvs(size=50, random_state=3), dtype=np.float32)
    assert np.allclose(np.asarray(rv.log_pdf(jnp.asarray(x))),
                       ref.logpdf(x), atol=1e-3, rtol=1e-3)
    assert np.allclose(np.asarray(rv.cdf(jnp.asarray(x))),
                       ref.cdf(x), atol=1e-3)
    # under jit (the compiled-round path)
    lp_jit = jax.jit(rv.log_pdf)(jnp.asarray(x))
    assert np.allclose(np.asarray(lp_jit), ref.logpdf(x), atol=1e-3)
    draws = np.asarray(jax.jit(
        lambda k: rv.sample(k, (5000,)))(key))
    assert abs(draws.mean() - ref.mean()) < 0.1
    # deterministic in the key
    d2 = np.asarray(jax.jit(lambda k: rv.sample(k, (5000,)))(key))
    np.testing.assert_array_equal(draws, d2)
    # picklable (SGE/dask transport, reference shims :27-32)
    import pickle
    rv2 = pickle.loads(pickle.dumps(rv))
    assert np.allclose(np.asarray(rv2.log_pdf(jnp.asarray(x))),
                       ref.logpdf(x), atol=1e-3)
    # discrete fallback routes through logpmf
    zipf = pt.RV("zipf", 2.5)
    assert zipf.discrete
    assert float(zipf.log_pdf(jnp.asarray(1.0))) == pytest.approx(
        float(ss.zipf(2.5).logpmf(1)), abs=1e-3)


def test_scipy_rv_e2e_abcsmc(db_path):
    """E2E: a Student-t prior (native) + a skewnorm prior (host fallback)
    drive a full VectorizedSampler ABCSMC run (VERDICT r3 item #4)."""
    def model(key, theta):
        noise = jax.random.normal(key, (theta.shape[0],)) * 0.1
        return {"y": theta[:, 0] + theta[:, 1] + noise}

    prior = pt.Distribution(a=pt.RV("t", 3.0),
                            b=pt.RV("skewnorm", 2.0))
    abc = pt.ABCSMC(model, prior, population_size=200, seed=4)
    abc.new(db_path, {"y": 1.0})
    hist = abc.run(max_nr_populations=3)
    df, w = hist.get_distribution()
    est = float((df["a"].to_numpy() + df["b"].to_numpy()) @ w)
    assert abs(est - 1.0) < 0.5


def test_binom_nbinom_degenerate_p():
    """p = 0 / p = 1 must give the correct log-pmf (~0 up to f32 gammaln
    roundoff), not NaN (0·log 0 guards)."""
    assert float(pt.RV("binom", 10, 1.0).log_pdf(
        jnp.asarray(10.0))) == pytest.approx(0.0, abs=1e-5)
    assert float(pt.RV("binom", 10, 0.0).log_pdf(
        jnp.asarray(0.0))) == pytest.approx(0.0, abs=1e-5)
    assert float(pt.RV("binom", 10, 1.0).log_pdf(jnp.asarray(9.0))) == -np.inf
    assert float(pt.RV("nbinom", 5, 1.0).log_pdf(
        jnp.asarray(0.0))) == pytest.approx(0.0, abs=1e-5)


def test_tabulated_rv_device_native(key):
    """TabulatedRV: device-native approximation of any continuous
    scipy.stats distribution — accurate tables, jit-safe everywhere
    (no host callbacks), picklable."""
    from pyabc_tpu.random_variables import TabulatedRV

    rv = pt.TabulatedRV("skewnorm", 3.0)
    ref = ss.skewnorm(3.0)
    x = np.asarray(ref.rvs(size=200, random_state=5), dtype=np.float32)
    inside = (x > rv._grid[0]) & (x < rv._grid[-1])
    assert np.allclose(np.asarray(rv.log_pdf(jnp.asarray(x)))[inside],
                       ref.logpdf(x)[inside], atol=2e-3, rtol=1e-3)
    assert np.allclose(np.asarray(rv.cdf(jnp.asarray(x))),
                       ref.cdf(x), atol=2e-3)
    # sampling distribution matches (KS-style quantile check)
    draws = np.asarray(jax.jit(lambda k: rv.sample(k, (40000,)))(key))
    for p in (0.1, 0.25, 0.5, 0.75, 0.9):
        assert abs(np.quantile(draws, p) - ref.ppf(p)) < 0.03
    # picklable without tables in the payload path issues
    import pickle
    rv2 = pickle.loads(pickle.dumps(rv))
    assert float(rv2.log_pdf(jnp.asarray(0.5))) == pytest.approx(
        float(rv.log_pdf(jnp.asarray(0.5))), abs=1e-6)
    # an untabulatable discrete support (wider than the 2^20 bound) is
    # rejected with a clear error
    with pytest.raises(ValueError, match="tabulation bound"):
        TabulatedRV("randint", 0, 3_000_000)


def test_tabulated_rv_discrete(key):
    """Discrete TabulatedRV (VERDICT r4 next #4): pmf table +
    cumsum-inverse sampling makes any bounded-support discrete
    scipy.stats prior device-native — exact pmf/cdf over the support,
    correct sampling frequencies, discrete=True for transitions."""
    rv = pt.TabulatedRV("hypergeom", 40, 12, 13)
    ref = ss.hypergeom(40, 12, 13)
    assert rv.discrete is True
    ks = np.arange(0, 13, dtype=np.float32)
    np.testing.assert_allclose(
        np.asarray(rv.log_pdf(jnp.asarray(ks))), ref.logpmf(ks),
        rtol=1e-4, atol=1e-5)
    # off-support and non-integral queries
    assert float(rv.log_pdf(jnp.asarray(-1.0))) == -np.inf
    assert float(rv.log_pdf(jnp.asarray(13.0))) == -np.inf
    np.testing.assert_allclose(
        np.asarray(rv.cdf(jnp.asarray(ks))), ref.cdf(ks), atol=1e-5)
    # sampling is jit-safe on device and matches the pmf
    draws = np.asarray(jax.jit(lambda k: rv.sample(k, (40000,)))(key))
    assert np.all(draws == np.round(draws))
    for k in (2, 3, 4, 5):
        freq = float(np.mean(draws == k))
        assert abs(freq - ref.pmf(k)) < 0.01
    # skellam spans negative integers — bounded by quantiles, still fine
    rv2 = pt.TabulatedRV("skellam", 2.0, 3.0)
    ref2 = ss.skellam(2.0, 3.0)
    for k in (-3.0, -1.0, 0.0, 2.0):
        assert float(rv2.log_pdf(jnp.asarray(k))) == pytest.approx(
            float(ref2.logpmf(k)), abs=1e-4)
    import pickle
    rv3 = pickle.loads(pickle.dumps(rv))
    assert float(rv3.log_pdf(jnp.asarray(4.0))) == pytest.approx(
        float(rv.log_pdf(jnp.asarray(4.0))), abs=1e-6)


def test_discrete_scipy_prior_on_callbackless_backend(db_path, monkeypatch):
    """RV('hypergeom', ...) on a callback-less backend (the relay) must
    auto-engage the discrete TabulatedRV and drive a full
    VectorizedSampler run (reference accepts any scipy.stats name
    anywhere, pyabc/random_variables.py:147-169)."""
    from pyabc_tpu.random_variables import ScipyRV

    monkeypatch.setattr(ScipyRV, "_callbacks_supported", False)
    rv = pt.RV("hypergeom", 40, 12, 13)
    from pyabc_tpu.random_variables import TabulatedRV
    assert isinstance(rv, TabulatedRV) and rv.discrete

    def model(key, theta):
        return {"y": theta[:, 0]
                + 0.5 * jax.random.normal(key, (theta.shape[0],))}

    abc = pt.ABCSMC(model, pt.Distribution(k=rv), population_size=200,
                    transitions=[pt.DiscreteRandomWalkTransition()],
                    sampler=pt.VectorizedSampler(), seed=3)
    abc.new(db_path, {"y": 5.0})
    h = abc.run(max_nr_populations=3)
    df, w = h.get_distribution()
    ks = df["k"].to_numpy()
    assert np.all(ks == np.round(ks))
    assert np.all((ks >= 0) & (ks <= 12))
    assert abs(float(ks @ w) - 5.0) < 2.0


def test_tabulated_rv_e2e_abcsmc(db_path):
    """A TabulatedRV prior drives a full run — the device-native path
    for arbitrary scipy.stats priors on callback-less backends."""
    def model(key, theta):
        return {"y": theta[:, 0]
                + 0.1 * jax.random.normal(key, (theta.shape[0],))}

    prior = pt.Distribution(a=pt.TabulatedRV("gumbel_r", 0.0, 0.5))
    abc = pt.ABCSMC(model, prior, population_size=200, seed=3)
    abc.new(db_path, {"y": 0.8})
    h = abc.run(max_nr_populations=3)
    df, w = h.get_distribution()
    assert abs(float(df["a"].to_numpy() @ w) - 0.8) < 0.4
