"""Shared visualization helpers (parity: pyabc/visualization/util.py)."""

from __future__ import annotations

from typing import List, Optional, Tuple, Union


def to_lists_or_default(histories, labels: Optional[Union[List, str]] = None
                        ) -> Tuple[list, list]:
    """Normalize (histories, labels) to equal-length lists
    (reference util.py ``to_lists_or_default``)."""
    if not isinstance(histories, (list, tuple)):
        histories = [histories]
    histories = list(histories)
    if labels is None:
        labels = [f"run {getattr(h, 'id', i)}"
                  for i, h in enumerate(histories)]
    elif isinstance(labels, str):
        labels = [labels]
    return histories, list(labels)


def format_plot_matrix(arr_ax, par_names: List[str]):
    """Hide inner tick labels of a square plot matrix and label the outer
    edge (reference kde.py matrix formatting)."""
    n = len(par_names)
    for i in range(n):
        for j in range(n):
            ax = arr_ax[i][j]
            if i < n - 1:
                ax.set_xlabel("")
                ax.tick_params(labelbottom=False)
            else:
                ax.set_xlabel(par_names[j])
            if j > 0:
                ax.set_ylabel("")
                ax.tick_params(labelleft=False)
            else:
                ax.set_ylabel(par_names[i])
    return arr_ax
