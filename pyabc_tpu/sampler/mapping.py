"""Map-based and executor-based samplers: the CPU black-box escape hatch.

Parity: pyabc/sampler/mapping.py:10-117 (``MappingSampler`` — any
``map``-like callable), pyabc/sampler/concurrent_future.py:5-71
(``ConcurrentFutureSampler``), pyabc/sampler/eps_mixin.py:6-123 (the
eval-parallel scheduler the futures samplers share).

These exist for simulators that cannot be expressed in JAX at all (external
binaries, R scripts, legacy Python): the per-candidate work is a host
closure farmed out over a map/executor, exactly the reference's model.  The
round kernel is NOT used; instead the sampler evaluates the same
proposal -> simulate -> distance -> accept pipeline per particle via a
host-side ``simulate_one`` closure built by the orchestrator
(``RoundKernel.host_simulate_one``).

For JAX-able models prefer VectorizedSampler/ShardedSampler — they are
orders of magnitude faster (see BASELINE.md).
"""

from __future__ import annotations

import logging
from concurrent.futures import Executor, ThreadPoolExecutor, as_completed
from typing import Callable, Optional

import jax
import numpy as np

from .base import RoundResult, Sample, Sampler

logger = logging.getLogger("ABC.Sampler")


class MappingSampler(Sampler):
    """STAT scheduling over any map-like callable (reference
    mapping.py:10-117): each map task evaluates one batch-of-1 candidate;
    tasks are submitted in waves until n are accepted."""

    def __init__(self, map_=map, mapper_pickles: bool = False,
                 wave_size: Optional[int] = None):
        super().__init__()
        self.map_ = map_
        self.mapper_pickles = mapper_pickles
        self.wave_size = wave_size

    def sample_until_n_accepted(self, n, round_fn, key, params,
                                max_eval=np.inf, all_accepted=False,
                                **kwargs) -> Sample:
        sample = Sample(record_rejected=self.record_rejected,
                        max_records=self.max_records)
        wave = self.wave_size or max(n, 16)

        def eval_one(seed: int):
            k = jax.random.fold_in(key, seed)
            rr = round_fn(k, params, 1, **(
                {"all_accepted": True} if all_accepted else {}))
            return jax.device_get(rr)

        seed = 0
        while sample.n_accepted < n:
            seeds = list(range(seed, seed + wave))
            seed += wave
            # device_get preserves the RoundResult pytree with numpy leaves
            for rr in self.map_(eval_one, seeds):
                sample.append_round(rr)
            if sample.nr_evaluations >= max_eval and sample.n_accepted < n:
                logger.warning("max_eval reached in MappingSampler")
                break
        self.nr_evaluations_ = sample.nr_evaluations
        return sample


class ConcurrentFutureSampler(Sampler):
    """DYN scheduling over a ``concurrent.futures.Executor`` (reference
    concurrent_future.py:5-71 + eps_mixin.py:6-123): keep
    ``client_max_jobs`` batches in flight, harvest as they complete, cancel
    stragglers once n are accepted — results accounted in submission order
    (the de-biasing protocol)."""

    def __init__(self, cfuture_executor: Optional[Executor] = None,
                 client_max_jobs: int = 8, batch_size: int = 1):
        super().__init__()
        self.executor = cfuture_executor
        self.client_max_jobs = int(client_max_jobs)
        self.batch_size = int(batch_size)

    def sample_until_n_accepted(self, n, round_fn, key, params,
                                max_eval=np.inf, all_accepted=False,
                                **kwargs) -> Sample:
        sample = Sample(record_rejected=self.record_rejected,
                        max_records=self.max_records)
        executor = self.executor or ThreadPoolExecutor(
            max_workers=self.client_max_jobs)
        owns = self.executor is None
        B = self.batch_size

        def eval_batch(seed: int):
            k = jax.random.fold_in(key, seed)
            return seed, jax.device_get(round_fn(
                k, params, B, **({"all_accepted": True}
                                 if all_accepted else {})))

        try:
            next_seed = 0
            in_flight = {}
            results = {}
            harvested = 0  # next submission id to account
            while True:
                # submission-order accounting (eps_mixin.py:62-81)
                while harvested in results:
                    sample.append_round(results.pop(harvested))
                    harvested += 1
                # all_accepted needs no special exit: every candidate is
                # accepted, so n_accepted reaches n exactly when enough
                # batches have been harvested (reference eps_mixin.py:62-81).
                if sample.n_accepted >= n or (
                        sample.nr_evaluations >= max_eval
                        and sample.n_accepted < n):
                    break
                while len(in_flight) < self.client_max_jobs:
                    fut = executor.submit(eval_batch, next_seed)
                    in_flight[fut] = next_seed
                    next_seed += 1
                done = next(as_completed(list(in_flight)))
                seed, rr = done.result()
                del in_flight[done]
                results[seed] = rr
            for fut in in_flight:
                fut.cancel()
        finally:
            if owns:
                executor.shutdown(wait=False, cancel_futures=True)
        self.nr_evaluations_ = sample.nr_evaluations
        return sample
