"""Docs-as-tests: every example script runs end to end (parity: the
reference executes its 9 example notebooks in CI, test/run_notebooks.sh)."""

import os
import runpy

import pytest

EXAMPLES = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples")


@pytest.mark.parametrize("script", sorted(
    f for f in os.listdir(EXAMPLES) if f.endswith(".py")))
def test_example_runs(script, monkeypatch):
    monkeypatch.setenv("ABC_EXAMPLE_POP", "200")
    monkeypatch.setenv("ABC_EXAMPLE_GENS", "3")
    runpy.run_path(os.path.join(EXAMPLES, script), run_name="__main__")
