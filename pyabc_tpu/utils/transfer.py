"""Host<->device transfer accounting.

The north-star budget is transfer-bound: the per-generation population
fetch rides a ~6-8 MB/s relay d2h link, so wire BYTES — not FLOPs — are
the lever that matters (BASELINE.md round-4 analysis).  This module keeps
process-global counters that the samplers' single choke points
(``fetch_to_host`` for d2h, the per-generation ``device_put`` for h2d)
increment, so regressions in wire bytes are machine-visible in the bench
JSON (VERDICT r4 next #5) instead of hiding inside wall-clock noise.

The reference has no analog — its sampler transport is pickled
process/network IO with no byte accounting (e.g.
pyabc/sampler/redis_eps/sampler.py result pipelines).
"""

from __future__ import annotations

import threading
import time

_lock = threading.Lock()
_state = {"d2h_bytes": 0, "d2h_s": 0.0, "d2h_calls": 0, "h2d_bytes": 0}


def _tree_nbytes(tree) -> int:
    import jax.tree_util as tu

    return sum(getattr(leaf, "nbytes", 0)
               for leaf in tu.tree_leaves(tree))


def record_d2h(nbytes: int, seconds: float):
    with _lock:
        _state["d2h_bytes"] += int(nbytes)
        _state["d2h_s"] += float(seconds)
        _state["d2h_calls"] += 1


def record_h2d(nbytes: int):
    with _lock:
        _state["h2d_bytes"] += int(nbytes)


def snapshot() -> dict:
    with _lock:
        return dict(_state)


def delta(before: dict, after: dict = None) -> dict:
    """Counter difference ``after - before`` (``after`` defaults to now)."""
    after = after if after is not None else snapshot()
    return {k: after[k] - before.get(k, 0) for k in _state}


class timed_d2h:
    """Context manager charging one device->host transaction: measures
    wall time and credits ``nbytes`` (computed by the caller from the
    fetched tree) to the d2h counters."""

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.seconds = time.perf_counter() - self._t0
        return False

    def commit(self, tree):
        record_d2h(_tree_nbytes(tree), self.seconds)
        return tree
