def open_only(spans):
    tok = spans.begin("ingest.queue")  # graftlint: allow(span-pairs)
    spans.begin("ingest.work")  # graftlint: allow(span-pairs)
    return tok
