"""Hot-op kernels (MXU-native formulations; pallas variants live here)."""

from .choice import (fast_weighted_choice, residual_weighted_choice,
                     systematic_weighted_choice)
from .kde import weighted_kde_logpdf, weighted_kde_logpdf_auto
from .quantile_sketch import (sketch_error_bound, sketch_topk_mask,
                              sketch_weighted_quantile)

__all__ = ["weighted_kde_logpdf", "weighted_kde_logpdf_auto",
           "fast_weighted_choice", "systematic_weighted_choice",
           "residual_weighted_choice", "sketch_weighted_quantile",
           "sketch_topk_mask", "sketch_error_bound"]
