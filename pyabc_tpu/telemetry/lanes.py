"""Device-resident telemetry lanes + the live in-dispatch progress word.

A ``run_mode="onedispatch"`` run is ONE ``lax.while_loop`` dispatch
(sampler/fused.py:build_onedispatch_run), so the host-side telemetry
stack — span tracer, GenerationTimeline, fleet snapshots — sees a
multi-minute run as a single opaque span.  This module is the
in-dispatch half of the observability story, in two parts:

**Telemetry lanes** (``tl_*`` wire lanes).  :func:`phase_wire_lanes` is
a traceable function the fused per-generation body calls after its
rejection loop: it emits per-generation work counters — simulations,
and a per-phase work-unit vector over :data:`PHASES` (simulate /
distance / eps-solve / refit / resample) — as extra wire lanes riding
the same ``[max_T]``-slot egress buffers as the population wire.  Every
lane is a pure arithmetic function of values the program already
computes (the round count is the only dynamic input), so lanes-on and
lanes-off programs produce BIT-IDENTICAL populations: no RNG ops, no
reductions over population data, nothing feeding back into the math.
The drain fetches them under ``wire.transfer.egress("telemetry")``
(O(bytes) per generation) and :func:`attribute_phases` normalizes the
work-unit vector onto the generation's measured wall to hydrate the
timeline's per-phase columns.

Honesty note: XLA exposes no per-op device clocks inside a compiled
while-loop, so per-phase *cycle* attribution is a device-exact work
model (dynamic round counts x static per-phase cost factors derived
from the program shape), normalized onto measured wall seconds — the
same flops-proportional attribution a profiler cost model uses, not a
hardware timer.  The counters themselves (rounds, simulations,
accepted, eps) are exact.

**Progress word** (:data:`PROGRESS`).  The only host-visible channel
out of an in-flight dispatch is a host callback: any device buffer read
blocks until the whole while-loop completes, so the one-dispatch driver
plants a ``jax.debug.callback`` at each generation boundary that calls
:func:`device_progress_update` with the generation index, epsilon,
accepted count, cumulative rounds and the run's *tag* (a traced
``ctl["run_tag"]`` scalar), which routes the update to that run's own
word in the process-global registry (lock-guarded dicts — the callback
must stay microseconds-cheap; a serve worker interleaving studies keeps
one word per run).  Nothing blocks on the run future.  A
:class:`ProgressPoller` daemon thread samples the word every
``$PYABC_TPU_PROGRESS_POLL_S`` seconds (default 0.5) and force-writes
the fleet snapshot, so ``abc-top --watch``, ``/api/fleet`` and the
Prometheus exposition show generation-level progress *during* the
dispatch; on pods every process publishes its own word and the reader
side merges them (:func:`merge_progress`).  The flight recorder embeds
the last word in its dump, so a ``kill -9`` post-mortem names the
generation that died.

Leaf-package rule: telemetry imports nothing from wire/parallel at
module level; jax is imported function-locally (the host-side helpers
must work in processes that never touch jax).
"""

from __future__ import annotations

import math
import os
import threading
import time
from typing import Callable, Dict, List, Optional

#: phases of one fused generation, in program order.  ``simulate``,
#: ``distance`` and ``screen`` scale with the rejection rounds
#: (``screen`` is the multi-fidelity cascade's low-fidelity stage,
#: zero-cost when screening is off); ``eps_solve`` / ``refit`` /
#: ``resample`` are once-per-generation adaptation work.
PHASES = ("simulate", "distance", "screen", "eps_solve", "refit",
          "resample")

#: wire-lane prefix; the store/drain exclude ``tl_*`` lanes from
#: population decode exactly like the ``sm_*`` summary lanes
LANE_PREFIX = "tl_"

#: polling cadence of the in-dispatch progress publisher (seconds)
POLL_ENV = "PYABC_TPU_PROGRESS_POLL_S"

#: master switch for the device lanes + progress callback (default on);
#: "0" compiles the exact pre-lanes program — the disabled-overhead gate
LANES_ENV = "PYABC_TPU_TELEMETRY_LANES"


def lanes_enabled() -> bool:
    """Whether device telemetry lanes (and the in-dispatch progress
    callback) are compiled into one-dispatch programs."""
    return os.environ.get(LANES_ENV, "1") not in ("0", "false", "no")


def poll_interval_s() -> float:
    try:
        return max(float(os.environ.get(POLL_ENV, "0.5")), 0.05)
    except ValueError:
        return 0.5


# ------------------------------------------------------------- device side

def phase_cost_model(*, B: int, n_target: int, d: int, s: int, M: int,
                     eps_mode: str, support_rows: int,
                     adaptive: bool,
                     fidelity: bool = False) -> Dict[str, float]:
    """Static per-phase cost factors for one generation, derived from
    the program shape (batch ``B``, population ``n_target``, parameter
    dim ``d``, summary-stat width ``s``, ``M`` models, the epsilon mode
    and the refit support size).  Units are arbitrary work units — only
    the RATIOS matter, because :func:`attribute_phases` normalizes onto
    the measured wall.  Factors marked ``per_round`` multiply the
    generation's dynamic round count on device."""
    sup = max(int(support_rows), 1)
    model = {
        # one proposal + forward simulation per candidate per round
        "simulate": {"per_round": float(B) * max(s, 1), "fixed": 0.0},
        # distance kernel over the candidate stats per round
        "distance": {"per_round": float(B) * max(s, 1), "fixed": 0.0},
        # multi-fidelity low-fidelity stage + threshold screen per
        # round; an unscreened program carries a zero-cost row so the
        # lane layout (and egress size) is mode-independent
        "screen": {"per_round": (float(B) * max(s, 1) if fidelity
                                 else 0.0),
                   "fixed": 0.0},
        # weighted quantile: O(n log n) sort (or O(n) sketch, but the
        # ratio distinction is below attribution noise); temperature:
        # bisection over the record ring; constant: free
        "eps_solve": {"per_round": 0.0,
                      "fixed": (0.0 if eps_mode == "constant"
                                else float(n_target)
                                * max(math.log2(max(n_target, 2)), 1.0))},
        # per-model KDE covariance + cholesky over the (possibly
        # capped) support; an adaptive distance refit rides here too
        "refit": {"per_round": 0.0,
                  "fixed": (float(M) * sup * d * d
                            + (float(B) * max(s, 1) if adaptive
                               else 0.0))},
        # deferred proposal-density correction: accepted rows x support
        "resample": {"per_round": 0.0,
                     "fixed": float(n_target) * sup * max(d, 1)},
    }
    return model


def phase_wire_lanes(rounds, B: int, cost_model: Dict[str, dict]):
    """Traceable ``tl_*`` lane dict for one generation: ``tl_sims``
    (i32 — candidate simulations, ``rounds * B``) and ``tl_phase``
    (f32[len(PHASES)] — per-phase work units, ``per_round * rounds +
    fixed``).  ``rounds`` is the only traced input; everything else is
    static, so the lanes add a handful of scalar mul/adds to the trace
    and touch no population math."""
    import jax.numpy as jnp

    r = rounds.astype(jnp.float32)
    phase = jnp.stack([
        jnp.float32(cost_model[name]["per_round"]) * r
        + jnp.float32(cost_model[name]["fixed"])
        for name in PHASES])
    return {"tl_sims": rounds * jnp.int32(B), "tl_phase": phase}


def attribute_phases(tl_phase, wall_s: float) -> Dict[str, float]:
    """Normalize one generation's work-unit vector onto its measured
    wall seconds: ``{phase: seconds}`` summing to ``wall_s`` (an
    all-zero vector attributes everything to ``simulate`` rather than
    dividing by zero)."""
    import numpy as np

    v = np.asarray(tl_phase, dtype=np.float64).reshape(-1)
    total = float(v.sum())
    out = {}
    for i, name in enumerate(PHASES):
        share = (float(v[i]) / total) if total > 0 else \
            (1.0 if name == "simulate" else 0.0)
        out[name] = share * float(wall_s)
    return out


# ----------------------------------------------------------- progress word

class RunProgress:
    """Per-run in-dispatch progress words, keyed by a run tag.

    One process may have SEVERAL one-dispatch runs in flight at once —
    a serve worker (``serve/worker.py``) interleaves studies, and two
    ``ABCSMC`` instances on threads share this module.  A single global
    word would let run B's callbacks clobber run A's progress, so
    ``begin()`` allocates a fresh integer *tag*, returns it, and the
    orchestrator threads it through the compiled program as a traced
    ``ctl["run_tag"]`` operand; the device callbacks hand it back to
    :meth:`update` so every run advances only its own word.

    ``read()`` with no tag keeps the legacy single-word shape (the
    freshest ACTIVE word, falling back to the freshest finished one) —
    the shape that lands in fleet snapshots, flight dumps and
    ``/api/fleet``; ``read(tag)`` isolates one run and ``read_all()``
    feeds the serve studies view.  Finished words are kept for a short
    tail (:data:`RunProgress._KEEP_FINISHED`) so post-run snapshots
    still see the terminal state, then evicted oldest-first.
    """

    #: lock-discipline contract, enforced by `abc-lint`
    _GUARDED_BY = {"_words": "_lock", "_current": "_lock",
                   "_next_tag": "_lock"}

    #: finished words retained for post-run reads before eviction
    _KEEP_FINISHED = 8

    def __init__(self):
        self._lock = threading.Lock()
        self._words: Dict[int, dict] = {}
        self._current: Optional[int] = None
        self._next_tag = 1

    def begin(self, *, t0: int, t_limit: int, run_id=None) -> int:
        """Arm a new word; returns its tag (a small positive int the
        dispatch carries as a traced operand — 0 is reserved for
        \"untagged\", which routes to the most recently armed word)."""
        with self._lock:
            tag = self._next_tag
            self._next_tag += 1
            now = time.time()
            self._words[tag] = {
                "active": True,
                "tag": tag,
                "t0": int(t0),
                "t_limit": int(t_limit),
                "gen": int(t0),
                "gens_done": 0,
                "eps": None,
                "accepted": None,
                "rounds": 0,
                "run_id": None if run_id is None else str(run_id),
                "started_unix": now,
                "updated_unix": now,
            }
            self._current = tag
            self._evict_locked()
            return tag

    def _evict_locked(self):
        finished = sorted(
            (t for t, w in self._words.items() if not w["active"]),
            key=lambda t: self._words[t]["updated_unix"])
        for t in finished[:max(len(finished) - self._KEEP_FINISHED, 0)]:
            del self._words[t]

    def update(self, gens_done: int, eps: float, accepted: int,
               rounds: int, tag: Optional[int] = None):
        """Advance one word; called from the debug-callback thread while
        the dispatch is in flight, so it must stay O(dict write).
        ``gens_done`` counts completed generations; ``gen`` is the
        absolute index of the last completed one.  ``tag`` 0/None means
        the most recently armed run (legacy untagged callbacks)."""
        with self._lock:
            key = self._current if not tag else int(tag)
            st = None if key is None else self._words.get(key)
            if st is None:
                return
            # keep the word monotone regardless of delivery order
            # (unordered callbacks may arrive out of order)
            gd = int(gens_done)
            if gd < st["gens_done"]:
                return
            st["gens_done"] = gd
            st["gen"] = st["t0"] + gd - 1
            st["eps"] = float(eps)
            st["accepted"] = int(accepted)
            st["rounds"] = max(int(rounds), st["rounds"])
            st["updated_unix"] = time.time()

    def finish(self, tag: Optional[int] = None):
        with self._lock:
            key = self._current if not tag else int(tag)
            st = None if key is None else self._words.get(key)
            if st is not None:
                st["active"] = False
                st["updated_unix"] = time.time()

    def reset(self):
        """Test isolation: forget every run's word."""
        with self._lock:
            self._words = {}
            self._current = None
            self._next_tag = 1

    def read(self, tag: Optional[int] = None) -> Optional[dict]:
        """``read(tag)`` → that run's word (or None).  ``read()`` → the
        legacy single-word view: freshest active word, else freshest
        finished one, else None."""
        with self._lock:
            if tag:
                st = self._words.get(int(tag))
                return None if st is None else dict(st)
            if not self._words:
                return None
            active = [w for w in self._words.values() if w["active"]]
            pick = max(active or list(self._words.values()),
                       key=lambda w: w["updated_unix"])
            return dict(pick)

    def read_all(self) -> List[dict]:
        """Every retained word, oldest tag first — the serve studies
        view's source."""
        with self._lock:
            return [dict(self._words[t]) for t in sorted(self._words)]


#: the process-global progress registry (one word per in-flight
#: one-dispatch run; a plain run keeps exactly one active)
PROGRESS = RunProgress()


def device_progress_update(gens_done, eps, accepted, rounds, written,
                           run_tag=None):
    """``jax.debug.callback`` target planted at each generation boundary
    of the one-dispatch while-loop (sampler/fused.py:gen_step).  Arrives
    with device scalars; must never raise — an observability callback
    that kills the dispatch it observes is worse than no callback.
    ``written`` gates out dead post-stop iterations (their repeated
    frontier values carry zeroed counters, not progress); ``run_tag``
    is the traced ``ctl["run_tag"]`` routing the update to its own
    run's word (0/None = most recently armed)."""
    try:
        if not bool(written):
            return
        PROGRESS.update(int(gens_done), float(eps), int(accepted),
                        int(rounds),
                        tag=None if run_tag is None else int(run_tag))
    except Exception:
        pass


class ProgressPoller:
    """Daemon thread publishing the progress word while a dispatch is in
    flight.  The main thread is blocked inside the first egress fetch
    for the whole device run, so WITHOUT this thread the fleet snapshot
    would freeze at the pre-dispatch state; with it, every poll tick
    that sees a fresh word force-writes the snapshot (the publisher's
    own throttle is bypassed — the cadence knob here IS the throttle).
    """

    def __init__(self, publish: Callable[[], object],
                 interval_s: Optional[float] = None):
        self._publish = publish
        self._interval = (poll_interval_s() if interval_s is None
                          else max(float(interval_s), 0.05))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_seen = -1.0

    def start(self) -> "ProgressPoller":
        t = threading.Thread(target=self._run, daemon=True,
                             name="abc-progress-poller")
        self._thread = t
        t.start()
        return self

    def _run(self):
        while not self._stop.wait(self._interval):
            word = PROGRESS.read()
            if word is None or not word.get("active"):
                continue
            if word["updated_unix"] <= self._last_seen:
                continue  # nothing new since the last publish
            self._last_seen = word["updated_unix"]
            try:
                self._publish()
            except Exception:
                pass  # a publish hiccup must not kill the poller

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
            self._thread = None


# -------------------------------------------------------------- fleet side

def merge_progress(words: List[Optional[dict]]) -> Optional[dict]:
    """Merge per-host progress words into one fleet view.  Pod processes
    run the same program in lockstep, so the merged word is the most
    recently updated ACTIVE word (falling back to the freshest inactive
    one); ``hosts_active`` counts processes still inside a dispatch."""
    live = [w for w in words if w]
    if not live:
        return None
    active = [w for w in live if w.get("active")]
    pick = max(active or live,
               key=lambda w: w.get("updated_unix", 0.0))
    merged = dict(pick)
    merged["hosts_active"] = len(active)
    merged["hosts_reporting"] = len(live)
    return merged
