"""The HBM capacity model: a pure ledger from run shape to peak bytes.

Nothing in here traces or compiles.  Every entry point is arithmetic
over the run's shape parameters, so the orchestrator (``smc.py``) and
the platform factory can consult it BEFORE the first ``jit`` — at pop
1e8 the f32 carry alone is tens of GB and the failure mode without a
model is an XLA OOM minutes into compilation.

The ledger names the population- and batch-proportional device
allocations of one engine step.  It is deliberately a first-order
model: per-component constants are chosen to match how the fused
programs actually allocate (verified against XLA's own
``memory_analysis()`` by the ``podstar_pop1e8`` bench row, which pins
``|predicted - measured| / measured <= 15%``), and every constant is a
named column in the ledger so a ``CapacityError`` shows WHERE the bytes
went, not just that they overflowed.

Budget resolution, in order:

- ``PYABC_TPU_HBM_BUDGET``  — explicit budget, used verbatim
  (suffixes ``K``/``M``/``G``/``T``, e.g. ``12G``; plain = bytes).
- ``jax.devices()[0].memory_stats()['bytes_limit']`` scaled by
  ``1 - PYABC_TPU_HBM_HEADROOM`` (default headroom 0.1) — the real-TPU
  auto-detect path.
- CPU rigs report no limit: budget 0 = unconstrained, every plan fits,
  zero behavioural drift for the test suite.
"""

from __future__ import annotations

import dataclasses
import math
import os
from collections import OrderedDict
from typing import Callable, Optional

from ..ops.precision import CARRY_ITEMSIZE, resolve_carry_precision

HBM_BUDGET_ENV = "PYABC_TPU_HBM_BUDGET"
HBM_HEADROOM_ENV = "PYABC_TPU_HBM_HEADROOM"

#: the at-rest precision ladder ``carry_precision=auto`` descends —
#: widest (exact) mode first, so a fitting f32 plan always wins
AUTO_LADDER = ("f32", "bf16", "int8")

#: round-budget headroom for the completability constraint: a fused /
#: one-dispatch generation proposes ``batch`` rows per device round and
#: stops at ``max_T`` rounds, so a geometry with
#: ``ceil(headroom * population / batch) > max_T`` cannot fill the
#: population — the block undershoots and the run bounces to the
#: per-generation path (which a multi-process pod cannot take at all).
#: The headroom multiplies the perfect-acceptance round count to absorb
#: the quantile schedule's ~alpha per-generation acceptance (~0.5) plus
#: in-block decay; plan() never emits a geometry below it.
ROUND_HEADROOM = 4.0

_SUFFIX = {"k": 1024, "m": 1024 ** 2, "g": 1024 ** 3, "t": 1024 ** 4}


def parse_bytes(text) -> int:
    """``'12G' -> 12884901888``; accepts K/M/G/T (binary), optional
    trailing ``b``/``ib``, or a plain byte count (int or float)."""
    if isinstance(text, (int, float)):
        return int(text)
    raw = str(text).strip().lower()
    if not raw:
        return 0
    for tail in ("ib", "b"):
        if raw.endswith(tail) and len(raw) > len(tail):
            raw = raw[: -len(tail)]
            break
    mult = 1
    if raw and raw[-1] in _SUFFIX:
        mult = _SUFFIX[raw[-1]]
        raw = raw[:-1]
    try:
        return int(float(raw) * mult)
    except ValueError:
        raise ValueError(
            f"{HBM_BUDGET_ENV}: cannot parse {text!r} as a byte count "
            f"(expected e.g. '12G', '900M', or plain bytes)") from None


def detect_hbm_bytes() -> int:
    """Physical per-device HBM bytes, or 0 when the backend does not
    report one (CPU rigs, older runtimes)."""
    try:
        import jax
        stats = jax.devices()[0].memory_stats()
    except Exception:
        return 0
    if not stats:
        return 0
    return int(stats.get("bytes_limit", 0) or 0)


def resolved_budget_bytes() -> int:
    """The effective per-device budget: explicit env verbatim, else
    detected HBM scaled by the headroom fraction, else 0
    (unconstrained)."""
    raw = os.environ.get(HBM_BUDGET_ENV, "").strip()
    if raw:
        return parse_bytes(raw)
    phys = detect_hbm_bytes()
    if phys <= 0:
        return 0
    headroom = float(os.environ.get(HBM_HEADROOM_ENV, "0.1"))
    headroom = min(max(headroom, 0.0), 0.9)
    return int(phys * (1.0 - headroom))


def _ceil_div(a: int, b: int) -> int:
    return -(-int(a) // max(int(b), 1))


def ledger(*, population: int, param_dim: int, stat_dim: int,
           engine: str = "fused", batch: int = 4096, K: int = 1,
           max_T: int = 32, carry_precision: str = "f32",
           devices: int = 1, donate: bool = True,
           telemetry_lanes: bool = False, wire_stats: bool = False,
           models: int = 1, support_cap: Optional[int] = None,
           record_rows: int = 0, cal_rows: int = 0,
           sim_mult: int = 4) -> "OrderedDict[str, int]":
    """Per-device peak-byte ledger for one engine step.

    Columns (all bytes, population terms divided across ``devices``):

    - ``carry_at_rest``  — the resident population carry: ``m`` i32 +
      ``log_weight`` f32 (never narrowed) + theta/distance/stats at the
      at-rest width.  Doubled when donation is off (XLA keeps input and
      output buffers live across the dispatch); the sequential engine
      re-uploads per generation, so it always pays the double and never
      compresses.
    - ``accept_window``  — the f32 working set of the accept/compact
      window: ``n + B`` rows (population plus one rejection batch) at
      full f32 lane width.  Compressed-carry decode promotion aliases
      into this window, so it is not double-counted.
    - ``round_batch``    — per-round proposal/simulation workspace,
      batch-proportional with a ``sim_mult``-state-copy allowance.
    - ``wire_egress``    — stacked per-generation wire slots (f16
      lanes): ``K`` slots for a fused block, ``max_T`` for a
      one-dispatch run, none for sequential.
    - ``refit_support``  — proposal-refit support rows (capped by
      ``support_cap``), replicated per device for the KDE
      cross-product, one set per model.
    - ``record_ring``    — stochastic-acceptance record ring rows.
    - ``fidelity_rings`` — low/full calibration rings.
    - ``telemetry``      — flat lane overhead when telemetry lanes are
      on (deliberately tiny; present so the toggle is visible).
    """
    if engine not in ("sequential", "fused", "onedispatch"):
        raise ValueError(f"capacity: unknown engine {engine!r}")
    n, d, s = int(population), int(param_dim), int(stat_dim)
    devices = max(int(devices), 1)
    B = max(int(batch), 1)
    mode = resolve_carry_precision(carry_precision)
    if mode == "auto":
        raise ValueError("ledger() needs a concrete carry_precision; "
                         "plan() resolves 'auto'")
    if engine == "sequential":
        mode = "f32"  # the host loop never stores a compressed carry
    w = CARRY_ITEMSIZE[mode]

    n_dev = _ceil_div(n, devices)
    b_dev = _ceil_div(B, devices)
    cap_dev = n_dev + b_dev

    mult = 2 if (engine == "sequential" or not donate) else 1
    carry_row = 4 + 4 + w * (d + 1 + s)        # m, log_weight, bulk
    window_row = 4 + 4 + 4 * (d + 1 + s)       # the f32 promotion width

    slots = {"sequential": 0, "fused": int(K),
             "onedispatch": int(max_T)}[engine]
    wire_row = 2 * d + 3 + (2 * s if wire_stats else 0)

    sup = n if support_cap is None else min(int(support_cap), n)

    out: "OrderedDict[str, int]" = OrderedDict()
    out["carry_at_rest"] = n_dev * carry_row * mult
    out["accept_window"] = cap_dev * window_row
    out["round_batch"] = b_dev * 4 * (d + s + 3) * int(sim_mult)
    out["wire_egress"] = slots * n_dev * wire_row
    out["refit_support"] = int(models) * sup * (4 * d + 8)
    out["record_ring"] = int(record_rows) * (4 * d + 16)
    out["fidelity_rings"] = 2 * int(cal_rows) * 8
    out["telemetry"] = 4096 if telemetry_lanes else 0
    return out


def predict_peak_bytes(**kwargs) -> int:
    """Sum of the :func:`ledger` columns — the model's predicted
    per-device peak for one engine step."""
    return sum(ledger(**kwargs).values())


@dataclasses.dataclass(frozen=True)
class CapacityPlan:
    """A (precision, geometry) point the budget admits."""
    carry_precision: str
    batch: int
    K: int
    max_T: int
    devices: int
    predicted_bytes: int
    budget_bytes: int        # 0 = unconstrained
    ledger: "OrderedDict[str, int]"
    note: str = ""

    @property
    def predicted_mb(self) -> float:
        return self.predicted_bytes / (1024.0 * 1024.0)


class CapacityError(RuntimeError):
    """No (batch, K, max_T, precision) point fits the HBM budget.

    Carries the full ledger of the smallest candidate tried at the
    pinned precision (``.ledger``), the resolved budget (``.budget``),
    the losing prediction (``.predicted``), the original request
    (``.request``) and — when a narrower at-rest mode WOULD fit — a
    ``.hint`` naming it, so the error is an instruction, not a wall.
    """

    def __init__(self, message: str, *, request: dict, ledger: dict,
                 budget: int, predicted: int, hint: Optional[str] = None):
        super().__init__(message)
        self.request = request
        self.ledger = ledger
        self.budget = budget
        self.predicted = predicted
        self.hint = hint


def _fmt_mb(b: int) -> str:
    return f"{b / (1024.0 * 1024.0):.1f} MB"


def _render_ledger(led: dict) -> str:
    width = max(len(k) for k in led)
    return "\n".join(f"    {k.ljust(width)}  {_fmt_mb(v):>10}"
                     for k, v in led.items())


def _batch_rungs(batch: int,
                 round_to_batch: Optional[Callable[[int], int]]):
    """Descending halvings of the requested rung, floored at 256 (or
    the requested batch when smaller), snapped to the sampler's valid
    rungs when a rounder is supplied."""
    floor = min(int(batch), 256)
    out, b = [], int(batch)
    for _ in range(12):
        snapped = int(round_to_batch(b)) if round_to_batch else b
        snapped = max(snapped, 1)
        if snapped not in out:
            out.append(snapped)
        if b <= floor:
            break
        b = max(b // 2, floor)
    return out


def plan(*, population: int, param_dim: int, stat_dim: int,
         engine: str = "fused", batch: Optional[int] = None, K: int = 1,
         max_T: int = 32, carry_precision: Optional[str] = None,
         devices: int = 1, budget: Optional[int] = None,
         round_to_batch: Optional[Callable[[int], int]] = None,
         round_headroom: Optional[float] = None,
         **lanes) -> CapacityPlan:
    """Choose the widest (precision, geometry) point fitting the budget.

    Search order: the precision ladder outermost (requested mode only,
    or f32 -> bf16 -> int8 for ``auto``), then batch rungs descending,
    then block ``K`` descending, then ``max_T`` descending — i.e. the
    plan keeps exactness first and the requested geometry second, and
    only narrows the at-rest carry when no f32 geometry fits.

    Round-bounded engines (fused, onedispatch) additionally face the
    COMPLETABILITY constraint: a candidate (batch, max_T) must satisfy
    ``ceil(round_headroom * population / batch) <= max_T`` (default
    :data:`ROUND_HEADROOM`) — shrinking the rung below it would trade
    an OOM for a guaranteed undershoot, which is the same failed run.
    The smallest-candidate bytes a :class:`CapacityError` reports (and
    hence ``.predicted``) honour the constraint too.

    ``budget=None`` resolves via :func:`resolved_budget_bytes`; a
    non-positive budget is unconstrained and returns the request
    verbatim (``auto`` resolving to f32).  Raises :class:`CapacityError`
    when nothing fits.
    """
    if batch is None:
        batch = min(int(population), 4096)
    mode = resolve_carry_precision(carry_precision)
    if budget is None:
        budget = resolved_budget_bytes()
    budget = int(budget or 0)
    headroom = max(float(ROUND_HEADROOM if round_headroom is None
                         else round_headroom), 1.0)

    def _completable(b: int, t: int) -> bool:
        if engine == "sequential":
            return True  # the host loop rounds until done
        return math.ceil(headroom * int(population) / max(int(b), 1)) \
            <= int(t)

    def _ledger_at(prec, b, k, t):
        return ledger(population=population, param_dim=param_dim,
                      stat_dim=stat_dim, engine=engine, batch=b, K=k,
                      max_T=t, carry_precision=prec, devices=devices,
                      **lanes)

    if budget <= 0:
        prec = "f32" if mode == "auto" else mode
        led = _ledger_at(prec, batch, K, max_T)
        return CapacityPlan(prec, int(batch), int(K), int(max_T),
                            int(devices), sum(led.values()), 0, led,
                            note="unconstrained")

    ladder = AUTO_LADDER if mode == "auto" else (mode,)
    rungs = _batch_rungs(batch, round_to_batch)
    ks = list(range(int(K), 0, -1))
    ts = [int(max_T)]
    while ts[-1] > 8:
        ts.append(max(ts[-1] // 2, 8))

    smallest = None  # ledger of the tiniest candidate at ladder[0]
    for prec in ladder:
        for b in rungs:
            for k in ks:
                for t in ts:
                    if not _completable(b, t):
                        continue
                    led = _ledger_at(prec, b, k, t)
                    total = sum(led.values())
                    if prec == ladder[0]:
                        if smallest is None or total < smallest[1]:
                            smallest = (led, total, b, k, t)
                    if total <= budget:
                        clamped = (prec != ladder[0] or b != batch
                                   or k != K or t != max_T)
                        note = ("clamped to fit budget" if clamped
                                else "fits as requested")
                        return CapacityPlan(prec, b, k, t, int(devices),
                                            total, budget, led, note)

    request = dict(population=population, param_dim=param_dim,
                   stat_dim=stat_dim, engine=engine, batch=batch, K=K,
                   max_T=max_T, carry_precision=mode, devices=devices,
                   **lanes)
    if smallest is None:
        # no (batch, max_T) point can even FILL the population within
        # the compiled round budget — a bytes budget never fixes that
        led = _ledger_at(ladder[0], batch, K, max_T)
        raise CapacityError(
            f"capacity: no (batch, max_T) point can fill population="
            f"{population} within {max_T} rounds at {headroom:.1f}x "
            f"headroom (engine={engine}); raise max_T or the batch "
            f"ceiling", request=request, ledger=led, budget=budget,
            predicted=sum(led.values()), hint=None)

    # nothing fits — find the narrowest mode that WOULD, for the hint
    hint = None
    for prec in AUTO_LADDER[1:]:
        if prec in ladder:
            continue
        for b in rungs:
            for t in ts:
                if not _completable(b, t):
                    continue
                total = sum(_ledger_at(prec, b, 1, t).values())
                if total <= budget:
                    hint = (f"PYABC_TPU_CARRY_PRECISION={prec} would "
                            f"fit (predicted {_fmt_mb(total)} <= budget "
                            f"{_fmt_mb(budget)})")
                    break
            if hint:
                break
        if hint:
            break

    led, total, b, k, t = smallest
    msg = (
        f"capacity: no (batch, K, max_T, precision) point fits the HBM "
        f"budget\n  population={population} devices={devices} "
        f"engine={engine} carry_precision={mode}\n"
        f"  budget: {_fmt_mb(budget)}\n"
        f"  smallest candidate tried: batch={b} K={k} max_T={t} -> "
        f"predicted {_fmt_mb(total)}\n{_render_ledger(led)}")
    if hint:
        msg += f"\n  hint: {hint}"
    raise CapacityError(msg, request=request, ledger=led, budget=budget,
                        predicted=total, hint=hint)
