"""Host<->device transfer + overlap accounting (the wire's ledger).

The north-star budget is transfer-bound: the per-generation population
fetch rides a ~6-8 MB/s relay d2h link, so wire BYTES — not FLOPs — are
the lever that matters (BASELINE.md round-4 analysis).  This module keeps
process-global counters that the samplers' single choke points
(``fetch_to_host`` for d2h, the per-generation ``device_put`` for h2d)
increment, so regressions in wire bytes are machine-visible in the bench
JSON instead of hiding inside wall-clock noise.

Absorbed from ``pyabc_tpu/utils/transfer.py`` (which re-exports this
module unchanged) when the streaming-ingest subsystem landed, and
extended with per-stage overlap accounting:

- ``compute_s``   — seconds fetches spent waiting for the PRODUCING
  computation before any byte moved.  ``fetch_to_host`` now syncs
  (``jax.block_until_ready``) before starting the transfer timer, so
  compute wait is no longer booked as transfer (VERDICT r5 #3: the cpu8
  row booked 22.2 s of device compute as "transfer" for 0.133 MB moved).
- ``fetch_s``     — pure post-sync transfer seconds.  ``d2h_s`` is kept
  as the same number: it is the historical key every existing consumer
  (bench rows, generation_transfer) reads, now with the fixed semantics.
- ``overlap_s``   — fetch seconds absorbed by a background ingest worker
  while the caller thread kept working (``wire.streaming``); the
  NON-overlapped wall share of the wire is ``fetch_s - overlap_s``.

``snapshot()``/``delta()`` also report the derived ``d2h_mb_per_s`` —
pure link bandwidth, meaningful now that the timer excludes compute.

The reference has no analog — its sampler transport is pickled
process/network IO with no byte accounting (e.g.
pyabc/sampler/redis_eps/sampler.py result pipelines).
"""

from __future__ import annotations

import threading
import time

_lock = threading.Lock()
_state = {"d2h_bytes": 0, "d2h_s": 0.0, "d2h_calls": 0, "h2d_bytes": 0,
          "compute_s": 0.0, "fetch_s": 0.0, "overlap_s": 0.0}


def _tree_nbytes(tree) -> int:
    import jax.tree_util as tu

    return sum(getattr(leaf, "nbytes", 0)
               for leaf in tu.tree_leaves(tree))


def record_d2h(nbytes: int, seconds: float):
    with _lock:
        _state["d2h_bytes"] += int(nbytes)
        _state["d2h_s"] += float(seconds)
        _state["fetch_s"] += float(seconds)
        _state["d2h_calls"] += 1


def record_h2d(nbytes: int):
    with _lock:
        _state["h2d_bytes"] += int(nbytes)


def record_compute(seconds: float):
    """Charge a pre-fetch sync wait (the producing computation)."""
    with _lock:
        _state["compute_s"] += float(seconds)


def record_overlap(seconds: float):
    """Credit fetch seconds that ran on a background ingest worker while
    the caller thread was NOT blocked on them (``StreamingIngest``)."""
    with _lock:
        _state["overlap_s"] += float(seconds)


def _derived(d: dict) -> dict:
    d["d2h_mb_per_s"] = (round(d["d2h_bytes"] / 1e6 / d["d2h_s"], 3)
                         if d.get("d2h_s", 0.0) > 1e-9 else 0.0)
    return d


def snapshot() -> dict:
    with _lock:
        return _derived(dict(_state))


def delta(before: dict, after: dict = None) -> dict:
    """Counter difference ``after - before`` (``after`` defaults to now).
    The derived ``d2h_mb_per_s`` is recomputed over the window."""
    after = after if after is not None else snapshot()
    return _derived({k: after[k] - before.get(k, 0) for k in _state})


class timed_d2h:
    """Context manager charging one device->host transaction: measures
    wall time and credits ``nbytes`` (computed by the caller from the
    fetched tree) to the d2h counters.  Callers must sync the producing
    computation BEFORE entering (``fetch_to_host`` does, charging the
    wait to ``compute_s``) so the measured seconds are pure transfer."""

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.seconds = time.perf_counter() - self._t0
        return False

    def commit(self, tree):
        record_d2h(_tree_nbytes(tree), self.seconds)
        return tree
