"""Content-addressed study cache: digest → posterior summary, tiered.

Duplicate submissions are the cheapest studies to serve: the digest
(:func:`pyabc_tpu.serve.spec.study_digest`) covers everything that can
move the posterior, so a digest hit IS the result — no queue slot, no
dispatch, no device time.  The worker keys entries by
``<digest>.<engine>`` (the two serving engines are statistically but
not bitwise equivalent, so entries never alias across them); these
classes are agnostic to the key's composition.

Two tiers (docs/serving.md "Data plane"):

- **tier-1** (:class:`StudyCache`) — a bounded in-memory LRU private
  to one worker, with per-worker directory persistence (one JSON file
  per key) so a restarted worker re-serves its own history.  The
  spill write is atomic (write-then-rename, the queue's crash-safety
  contract) and CRC-framed, so a SIGKILL mid-spill can never leave a
  torn file that poisons restart warmth — a bad frame reads as a
  miss and is unlinked.
- **tier-2** (:class:`SharedResultStore`) — a shared content-
  addressed store on the serve mount, published on study completion,
  so *any* worker serves *any* tenant's duplicate warm, not just the
  worker that first ran it.  Publishes are write-then-hardlink with
  single-writer-wins semantics on digest collision (two workers
  finishing the same digest concurrently: the first publish is the
  entry, the loser discards its copy — the engines are deterministic
  per digest, so either copy is correct; first-wins just makes the
  choice stable).  Reads are CRC-verified and fall back to dispatch
  on corruption (the corrupt file is unlinked so the next completion
  republishes).

:class:`TieredStudyCache` composes them: get walks t1 → t2
(promoting a t2 hit into t1), put inserts into t1 and publishes to
t2.  Hit/miss/eviction counters land in the ``serve_*`` telemetry
namespace (fleet snapshots, ``abc-top``, ``/api/serve``, Prometheus
``pyabc_tpu_serve_*``), with per-tier hit counters feeding the
``serve_cache_hit_ratio_t1``/``_t2`` gauges.

Capacity knob: ``PYABC_TPU_SERVE_CACHE_SIZE`` (tier-1 entries,
default 64).  Tier-2 is unbounded by count (entries are small summary
JSONs; retention is the operator's mount policy).
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import zlib
from collections import OrderedDict
from typing import Optional, Tuple

from ..telemetry.metrics import REGISTRY

#: cache capacity env knob (tier-1 entries)
CACHE_SIZE_ENV = "PYABC_TPU_SERVE_CACHE_SIZE"

_DEFAULT_CAPACITY = 64


def cache_capacity() -> int:
    try:
        return max(int(os.environ.get(CACHE_SIZE_ENV,
                                      str(_DEFAULT_CAPACITY))), 1)
    except ValueError:
        return _DEFAULT_CAPACITY


# ---------------------------------------------------------------------------
# CRC framing, shared by both tiers' on-disk entries
# ---------------------------------------------------------------------------

def _frame(summary: dict) -> str:
    """Serialize a summary with a CRC32 over its canonical JSON — the
    frame a reader can verify without trusting the filesystem."""
    body = json.dumps(summary, sort_keys=True)
    return json.dumps({"crc": zlib.crc32(body.encode("utf-8")),
                       "summary": json.loads(body)})


def _unframe(text: str) -> Optional[dict]:
    """Decode a framed entry; ``None`` on a torn/corrupt/legacy file
    (any byte flip moves the CRC)."""
    try:
        payload = json.loads(text)
        body = json.dumps(payload["summary"], sort_keys=True)
        if zlib.crc32(body.encode("utf-8")) != int(payload["crc"]):
            return None
        return payload["summary"]
    except (ValueError, KeyError, TypeError):
        return None


def _write_frame(root: str, summary: dict) -> str:
    """Write a framed entry to a fresh tmp file under ``root`` and
    return its path — the caller renames (tier-1 spill) or hardlinks
    (tier-2 publish) it into place."""
    fd, tmp = tempfile.mkstemp(dir=root, suffix=".tmp")
    with os.fdopen(fd, "w", encoding="utf-8") as f:
        f.write(_frame(summary))
    return tmp


class StudyCache:
    """Tier-1: bounded LRU of study results keyed by content digest.

    ``get`` counts a hit or a miss (instance ledger + the ``serve_*``
    registry counters); ``put`` inserts and optionally persists.  A
    memory miss falls through to the persistence directory before
    counting as a miss — a warm DISK is still a served duplicate.
    Spill files are CRC-framed and written atomically (module
    docstring): a torn or bit-flipped spill reads as a miss and is
    unlinked, never served.
    """

    #: lock-discipline contract, enforced by `abc-lint`
    _GUARDED_BY = {"_entries": "_lock", "_hits": "_lock",
                   "_misses": "_lock", "_evictions": "_lock"}

    def __init__(self, capacity: Optional[int] = None,
                 root: Optional[str] = None):
        self.capacity = (cache_capacity() if capacity is None
                         else max(int(capacity), 1))
        self.root = root
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, dict]" = OrderedDict()
        self._hits = 0
        self._misses = 0
        self._evictions = 0
        if root:
            os.makedirs(os.path.join(root), exist_ok=True)

    # ---- persistence -----------------------------------------------------

    def _path(self, digest: str) -> Optional[str]:
        return None if not self.root else os.path.join(
            self.root, f"{digest}.json")

    def _load_persisted(self, digest: str) -> Optional[dict]:
        path = self._path(digest)
        if path is None or not os.path.exists(path):
            return None
        try:
            with open(path, encoding="utf-8") as f:
                summary = _unframe(f.read())
        except UnicodeDecodeError:
            summary = None  # bit rot past valid utf-8: corrupt
        except OSError:
            return None
        if summary is None:
            # torn/corrupt spill: poison for restart warmth — unlink
            # so the next put rewrites a clean frame
            REGISTRY.counter(
                "serve_cache_spill_corrupt_total",
                "tier-1 spill files that failed CRC verification").inc()
            try:
                os.unlink(path)
            except OSError:
                pass
        return summary

    def _persist(self, digest: str, summary: dict):
        path = self._path(digest)
        if path is None:
            return
        try:
            tmp = _write_frame(self.root, summary)
            os.replace(tmp, path)  # atomic on POSIX
        except OSError:
            pass  # persistence is an optimization, never a failure

    # ---- core ------------------------------------------------------------

    def get(self, digest: str) -> Optional[dict]:
        with self._lock:
            entry = self._entries.get(digest)
            if entry is not None:
                self._entries.move_to_end(digest)
                self._hits += 1
                REGISTRY.counter(
                    "serve_cache_hits_total",
                    "duplicate studies served from the content-"
                    "addressed cache").inc()
                return dict(entry)
        persisted = self._load_persisted(digest)
        with self._lock:
            if persisted is not None:
                self._insert_locked(digest, persisted)
                self._hits += 1
                REGISTRY.counter(
                    "serve_cache_hits_total",
                    "duplicate studies served from the content-"
                    "addressed cache").inc()
                return dict(persisted)
            self._misses += 1
            REGISTRY.counter(
                "serve_cache_misses_total",
                "study digests not found in the cache").inc()
            return None

    def put(self, digest: str, summary: dict) -> str:
        """Insert; returns the tier the entry landed in (``"t1"`` —
        the lifecycle trace's ``published(tier)`` field)."""
        with self._lock:
            self._insert_locked(digest, dict(summary))
        self._persist(digest, summary)
        return "t1"

    def _insert_locked(self, digest: str, summary: dict):
        self._entries[digest] = summary
        self._entries.move_to_end(digest)
        while len(self._entries) > self.capacity:
            self._entries.popitem(last=False)
            self._evictions += 1
            REGISTRY.counter(
                "serve_cache_evictions_total",
                "study results dropped by the cache LRU").inc()

    def stats(self) -> dict:
        with self._lock:
            looked = self._hits + self._misses
            return {
                "hits": self._hits,
                "misses": self._misses,
                "evictions": self._evictions,
                "size": len(self._entries),
                "capacity": self.capacity,
                "hit_ratio": (self._hits / looked) if looked else 0.0,
            }


class SharedResultStore:
    """Tier-2: shared content-addressed result store on the serve
    mount (module docstring).  One CRC-framed JSON file per cache key;
    publish is atomic with single-writer-wins on collision; reads
    verify the frame and treat corruption as a miss (unlinking the bad
    file so a future completion republishes)."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, key: str) -> str:
        return os.path.join(self.root, f"{key}.json")

    def publish(self, key: str, summary: dict) -> bool:
        """Publish a completed study's summary; returns ``True`` if
        this call created the entry, ``False`` on a digest collision
        (an equal-digest study finished first — first writer wins and
        this copy is discarded) or a filesystem error (publishing is
        an optimization, never a failure)."""
        path = self._path(key)
        if os.path.exists(path):
            REGISTRY.counter(
                "serve_cache_t2_collisions_total",
                "tier-2 publishes dropped because an equal-digest "
                "entry already existed (first writer won)").inc()
            return False
        tmp = None
        try:
            tmp = _write_frame(self.root, summary)
            # hardlink publish: link(2) fails with EEXIST instead of
            # overwriting, so two racing publishers resolve to exactly
            # one winner with no torn intermediate state
            try:
                os.link(tmp, path)
            except FileExistsError:
                REGISTRY.counter(
                    "serve_cache_t2_collisions_total",
                    "tier-2 publishes dropped because an equal-digest "
                    "entry already existed (first writer won)").inc()
                return False
            except OSError:
                # mount without hardlinks: fall back to rename (still
                # atomic; the racing window collapses to last-wins,
                # which is equally correct — both copies verify)
                os.replace(tmp, path)
                tmp = None
            REGISTRY.counter(
                "serve_cache_t2_published_total",
                "study results published into the shared tier-2 "
                "store").inc()
            return True
        except OSError:
            return False
        finally:
            if tmp is not None:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass

    def get(self, key: str) -> Optional[dict]:
        path = self._path(key)
        try:
            with open(path, encoding="utf-8") as f:
                summary = _unframe(f.read())
        except UnicodeDecodeError:
            summary = None  # bit rot past valid utf-8: corrupt
        except OSError:
            return None
        if summary is None:
            # CRC mismatch: serve nothing from a corrupt entry — fall
            # back to dispatch and make room for a clean republish
            REGISTRY.counter(
                "serve_cache_t2_corrupt_total",
                "tier-2 entries that failed CRC verification").inc()
            try:
                os.unlink(path)
            except OSError:
                pass
        return summary

    def verify_all(self) -> Tuple[int, int]:
        """Walk the store and CRC-check every entry — the chaos
        soak's integrity probe.  Returns ``(ok, corrupt)``; corrupt
        entries are left in place (``get`` unlinks on demand)."""
        ok = corrupt = 0
        try:
            names = sorted(os.listdir(self.root))
        except OSError:
            return (0, 0)
        for name in names:
            if not name.endswith(".json"):
                continue
            try:
                with open(os.path.join(self.root, name),
                          encoding="utf-8") as f:
                    good = _unframe(f.read()) is not None
            except UnicodeDecodeError:
                good = False
            except OSError:
                continue
            if good:
                ok += 1
            else:
                corrupt += 1
        return (ok, corrupt)

    def size(self) -> int:
        try:
            return sum(1 for n in os.listdir(self.root)
                       if n.endswith(".json"))
        except OSError:
            return 0


class TieredStudyCache:
    """The worker's cache surface: tier-1 LRU in front of the shared
    tier-2 store.  ``lookup`` reports WHICH tier hit so the worker can
    label ``served_from`` (``cache`` = tier-1, ``cache_t2`` = shared
    store); a t2 hit is promoted into t1 so the next duplicate on
    this worker is a t1 hit."""

    def __init__(self, capacity: Optional[int] = None,
                 root: Optional[str] = None,
                 shared_root: Optional[str] = None):
        self.t1 = StudyCache(capacity=capacity, root=root)
        self.t2 = (SharedResultStore(shared_root)
                   if shared_root else None)
        self._t2_hits = 0

    def lookup(self, key: str) -> Tuple[Optional[dict], Optional[str]]:
        summary = self.t1.get(key)
        if summary is not None:
            return summary, "t1"
        if self.t2 is not None:
            summary = self.t2.get(key)
            if summary is not None:
                self._t2_hits += 1
                REGISTRY.counter(
                    "serve_cache_t2_hits_total",
                    "duplicate studies served from the shared tier-2 "
                    "store").inc()
                self.t1.put(key, summary)  # promote: next hit is t1
                return summary, "t2"
        return None, None

    def get(self, key: str) -> Optional[dict]:
        return self.lookup(key)[0]

    def put(self, key: str, summary: dict) -> str:
        """Insert into t1 and publish to the shared tier; returns the
        deepest tier reached (``"t2"`` when this call created the
        shared entry, else ``"t1"``) for trace attribution."""
        self.t1.put(key, summary)
        if self.t2 is not None and self.t2.publish(key, summary):
            return "t2"
        return "t1"

    def stats(self) -> dict:
        s = self.t1.stats()
        lookups = s["hits"] + s["misses"]
        t1_hits = s["hits"]
        hits = t1_hits + self._t2_hits
        # a t2 hit was counted as a t1 miss by the inner cache; at the
        # tier surface it is a hit — misses here mean "dispatched"
        misses = max(s["misses"] - self._t2_hits, 0)
        return {
            **s,
            "hits": hits,
            "misses": misses,
            "t1_hits": t1_hits,
            "t2_hits": self._t2_hits,
            "t2_size": self.t2.size() if self.t2 is not None else 0,
            "hit_ratio": (hits / lookups) if lookups else 0.0,
            "hit_ratio_t1": (t1_hits / lookups) if lookups else 0.0,
            "hit_ratio_t2": (self._t2_hits / lookups) if lookups
            else 0.0,
        }
