"""Clean twin: the out-of-band call is justified inline (debug
tooling replaying the calibrator's own function is legitimate)."""

from ..fidelity import screen_threshold


def replay_threshold(cal_lo, cal_full, eps):
    return screen_threshold(cal_lo, cal_full, eps, q=0.5, margin=1.0,  # graftlint: allow(fidelity-discipline)
                            min_corr=0.0, min_pairs=1)
