"""Admission load-shedding: reject fast when the fleet is saturated.

Backpressure (``QueueFull``) and per-tenant quotas protect the QUEUE;
they say nothing about whether the fleet behind it is keeping up.
Under sustained overload a deep-but-under-limit queue just converts
arrival excess into unbounded latency — every admitted study waits
longer, no study is served better.  The shedding controller instead
rejects at the door, *with a price quote*: :class:`ServeOverloaded`
carries a computed ``retry_after_s`` so a well-behaved submitter backs
off proportionally to how far past the SLO the fleet is, and the
studies that ARE admitted keep their latency.

Two independent triggers, both opt-in (unset knob = disabled, zero
behavior change):

- **partition depth** — ``PYABC_TPU_SERVE_SLO_DEPTH``: shed when the
  target partition already holds this many pending studies.  Per
  partition, not global: the shard map (``serve/shards.py``) keys
  equal digests to one partition, so a hot content bucket sheds while
  the rest of the fleet keeps admitting.
- **served p99** — ``PYABC_TPU_SERVE_SLO_P99_MS``: shed when the
  fleet's rolling served-study p99 (workers publish per-worker
  snapshots under ``<serve root>/slo/``; the submitter reads the max
  of the fresh ones) breaches the latency SLO.  This is the
  closed-loop signal: depth says the queue is long, p99 says the
  users are already hurting.

A shed is **distinct from a quota rejection**: quota says *this
tenant* is over its share, shed says *the system* is over its SLO —
different counters (``serve_shed_total`` vs
``serve_queue_rejected_total``), different exception types, different
operator responses.  ``ServeOverloaded`` subclasses
:class:`~pyabc_tpu.serve.queue.QueueFull` so existing retry loops
keep working, and adds ``retry_after_s``.

``retry_after_s`` scales with the breach:
``PYABC_TPU_SERVE_SHED_RETRY_S`` (default 2 s) multiplied by the
overload ratio (depth/limit or p99/SLO) — twice over the SLO quotes
twice the back-off.  All knobs documented in ``docs/serving.md``
("Data plane").
"""

from __future__ import annotations

import json
import math
import os
import tempfile
import time
from typing import Optional, Sequence

from ..telemetry.metrics import REGISTRY
from .queue import QueueFull

#: per-partition pending-depth SLO; shed submissions past it (0/unset
#: disables depth shedding)
SLO_DEPTH_ENV = "PYABC_TPU_SERVE_SLO_DEPTH"

#: fleet rolling served-p99 SLO in milliseconds; shed while breached
#: (0/unset disables latency shedding)
SLO_P99_MS_ENV = "PYABC_TPU_SERVE_SLO_P99_MS"

#: base retry-after quote in seconds, scaled by the overload ratio
SHED_RETRY_S_ENV = "PYABC_TPU_SERVE_SHED_RETRY_S"

_DEFAULT_RETRY_S = 2.0

#: a per-worker latency snapshot older than this is a dead worker's
#: last word, not a live signal — ignored by the fleet read
_SNAPSHOT_FRESH_S = 60.0


class ServeOverloaded(QueueFull):
    """The fleet is past its SLO — come back in ``retry_after_s``."""

    def __init__(self, message: str, retry_after_s: float,
                 reason: str = "overload"):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)
        self.reason = reason


def percentile(values: Sequence[float], q: float) -> float:
    """Nearest-rank percentile (the worker-side rolling p99)."""
    if not values:
        return 0.0
    vs = sorted(values)
    idx = min(int(math.ceil(q * len(vs))) - 1, len(vs) - 1)
    return float(vs[max(idx, 0)])


def _slo_dir(root: str) -> str:
    return os.path.join(root, "slo")


def publish_latency_snapshot(root: str, worker_id: str,
                             walls_ms: Sequence[float],
                             now: Optional[float] = None) -> Optional[str]:
    """Worker side: atomically publish this worker's rolling served-
    latency percentiles under ``<serve root>/slo/<worker>.json`` so
    any submitter on the mount can price admission without talking to
    the worker.  Best-effort — a failed publish never fails a serve."""
    sdir = _slo_dir(root)
    path = os.path.join(sdir, f"{worker_id}.json")
    payload = {
        "worker": worker_id,
        "n": len(walls_ms),
        "p50_ms": round(percentile(walls_ms, 0.50), 3),
        "p99_ms": round(percentile(walls_ms, 0.99), 3),
        "ts": time.time() if now is None else now,
    }
    try:
        os.makedirs(sdir, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=sdir, suffix=".tmp")
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            json.dump(payload, f)
        os.replace(tmp, path)
        return path
    except OSError:
        return None


def fleet_p99_ms(root: str, now: Optional[float] = None) -> float:
    """Submitter side: the fleet's rolling served p99 — the max over
    fresh per-worker snapshots (a fleet is as slow as its slowest
    worker; max also can't be gamed down by adding idle workers)."""
    sdir = _slo_dir(root)
    now = time.time() if now is None else now
    worst = 0.0
    try:
        names = os.listdir(sdir)
    except OSError:
        return 0.0
    for name in names:
        if not name.endswith(".json"):
            continue
        try:
            with open(os.path.join(sdir, name), encoding="utf-8") as f:
                snap = json.load(f)
            if now - float(snap.get("ts", 0.0)) > _SNAPSHOT_FRESH_S:
                continue
            worst = max(worst, float(snap.get("p99_ms", 0.0)))
        except (OSError, ValueError, TypeError):
            continue  # torn concurrent publish: skip
    return worst


def sweep_snapshots(root: str, liveness: Optional[dict] = None,
                    now: Optional[float] = None,
                    fresh_s: float = _SNAPSHOT_FRESH_S) -> int:
    """GC ``slo/<worker>.json`` latency snapshots (scheduler tick).

    Two reasons to unlink a snapshot, both real leaks the tombstone
    sweep never covered: (a) its worker is DEAD by the fleet liveness
    join — reaped immediately, because inside the freshness window a
    just-died worker's last (often worst) p99 still pollutes the
    fleet max and sheds traffic a healthy fleet could take; (b) it is
    simply stale past ``fresh_s`` — already ignored by
    :func:`fleet_p99_ms`, but accumulating forever on a long-lived
    serve root as workers come and go.

    ``liveness`` maps worker id (``<host>_<pid>``, the snapshot's
    filename stem) → alive, the shape
    ``sched.scheduler.worker_liveness`` returns; ``None`` skips the
    dead-worker reap and only ages out stale files."""
    sdir = _slo_dir(root)
    now = time.time() if now is None else now
    n = 0
    try:
        names = os.listdir(sdir)
    except OSError:
        return 0
    for name in names:
        if not name.endswith(".json"):
            continue
        path = os.path.join(sdir, name)
        worker = name[:-len(".json")]
        dead = (liveness is not None and worker in liveness
                and not liveness[worker])
        stale = False
        if not dead:
            try:
                with open(path, encoding="utf-8") as f:
                    snap = json.load(f)
                stale = now - float(snap.get("ts", 0.0)) > fresh_s
            except (OSError, ValueError, TypeError):
                stale = True  # unreadable: reap it
        if dead or stale:
            try:
                os.unlink(path)
                n += 1
            except OSError:
                continue  # another sweeper won the race
    if n:
        REGISTRY.counter(
            "serve_slo_snapshots_swept_total",
            "dead/stale per-worker latency snapshots reaped").inc(n)
    return n


def _env_pos(name: str) -> Optional[float]:
    raw = os.environ.get(name)
    if not raw:
        return None
    try:
        val = float(raw)
    except ValueError:
        return None
    return val if val > 0 else None


def slo_p99_ms_configured() -> Optional[float]:
    """The configured end-to-end latency SLO
    (``$PYABC_TPU_SERVE_SLO_P99_MS``), or ``None`` — shared by the
    admission controller and the trace fold's SLO burn ledger."""
    return _env_pos(SLO_P99_MS_ENV)


class AdmissionController:
    """The shed decision, evaluated at submit time (queue side).

    Disabled (both SLO knobs unset) it is a no-op — the data plane
    behaves exactly as before.  Enabled, :meth:`check` raises
    :class:`ServeOverloaded` with a computed ``retry_after_s`` when
    either trigger fires, and counts the shed in
    ``serve_shed_total``."""

    def __init__(self, root: str,
                 slo_depth: Optional[int] = None,
                 slo_p99_ms: Optional[float] = None,
                 retry_s: Optional[float] = None):
        self.root = root
        self.slo_depth = (slo_depth if slo_depth is not None
                          else _env_pos(SLO_DEPTH_ENV))
        self.slo_p99_ms = (slo_p99_ms if slo_p99_ms is not None
                           else _env_pos(SLO_P99_MS_ENV))
        retry = (retry_s if retry_s is not None
                 else _env_pos(SHED_RETRY_S_ENV))
        self.retry_s = _DEFAULT_RETRY_S if retry is None else retry

    def enabled(self) -> bool:
        return bool(self.slo_depth or self.slo_p99_ms)

    def _shed(self, reason: str, message: str, ratio: float):
        REGISTRY.counter(
            "serve_shed_total",
            "study submissions shed by SLO admission control").inc()
        raise ServeOverloaded(
            message,
            retry_after_s=round(self.retry_s * max(ratio, 1.0), 2),
            reason=reason)

    def check(self, partition_depth: int, partition: int = 0):
        """Raise :class:`ServeOverloaded` if admitting one more study
        into this partition would violate an SLO; no-op otherwise."""
        if self.slo_depth and partition_depth >= self.slo_depth:
            self._shed(
                "depth",
                f"partition p{partition:04d} at depth "
                f"{partition_depth} >= SLO {int(self.slo_depth)}",
                partition_depth / self.slo_depth)
        if self.slo_p99_ms:
            p99 = fleet_p99_ms(self.root)
            if p99 > self.slo_p99_ms:
                self._shed(
                    "p99",
                    f"fleet served p99 {p99:.0f}ms > SLO "
                    f"{self.slo_p99_ms:.0f}ms",
                    p99 / self.slo_p99_ms)
