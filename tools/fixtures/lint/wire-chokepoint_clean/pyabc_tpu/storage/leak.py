import jax


def grab(arr, transfer):
    host = jax.device_get(arr)  # graftlint: allow(wire-chokepoint)
    with transfer.egress("particles"):  # graftlint: allow(wire-chokepoint)
        pass
    return host
