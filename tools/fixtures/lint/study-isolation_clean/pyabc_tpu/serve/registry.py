"""Same shapes as the bad fixture, either suppressed or moved onto an
instance — must be silent."""

import collections

_ENGINES = {}  # graftlint: allow(study-isolation)
_RESULTS = []  # graftlint: allow(study-isolation)
_BY_TENANT = collections.defaultdict(list)  # graftlint: allow(study-isolation)
_PROCESS_WIDE = set()  # study-state-ok

# immutable module constants never fire
MAX_DEPTH = 256
_STOP_CODES = (0, 1, 2, 3)


class Registry:
    # class-body literals are declarative metadata, not shared state
    _GUARDED_BY = {"_engines": "_lock"}

    def __init__(self):
        # instance state is the sanctioned home for mutables
        self._engines = {}
        self._results = []
        self._by_tenant = collections.defaultdict(list)

    def submit(self, digest, result):
        staged = {}
        staged[digest] = result
        self._results.append(staged)
