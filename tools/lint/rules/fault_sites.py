"""Rule ``fault-sites``: every fault site is planted, bounded, and
tested.

``pyabc_tpu/resilience/faults.py`` defines the injection sites the
chaos harness drives (``faults.SITES``).  A site that exists in the
tuple but is never planted in the code, or is planted OUTSIDE a
recovery boundary, or is exercised by zero tests, gives false
confidence: chaos runs "pass" while the failure mode they claim to
cover is untested.  This rule closes the loop, the same way
``retry-sites`` pins dispatches to the retry wrapper:

1. **Completeness** — every ``SITE_* = "..."`` constant in faults.py
   is listed in ``SITES``, and ``SITES`` has no strings without a
   constant (parsed statically, no import);
2. **Planting + boundary** — each site's constant appears in its
   owning module TOGETHER with that site's recovery-boundary marker
   (retry wrapper, journal append, digest verification, preemption
   ledger...), per the manifest below;
3. **Test coverage** — each site's literal string appears in at least
   one file under ``tests/`` or in ``tools/chaos_soak.py`` (whose
   deterministic subset runs in tier-1 via
   ``tests/test_chaos_soak.py``);
4. **Docs** — each site's literal string appears in
   ``docs/resilience.md`` (the site x action matrix).

Findings are project-level (line 0), so inline suppression does not
apply; a new site must get a MANIFEST entry instead.
"""

from __future__ import annotations

import os
import re
import sys

from ..core import Finding, Rule, register

#: site constant -> (planting file under pyabc_tpu/, markers that must
#: ALL appear in that file: the constant itself plus the recovery
#: boundary that makes an injected fault survivable)
MANIFEST = {
    "SITE_DISPATCH": ("sampler/base.py",
                      ("SITE_DISPATCH", "_retry.call(")),
    "SITE_FETCH": ("sampler/base.py",
                   ("SITE_FETCH", "shared_policy().call(")),
    "SITE_APPEND": ("storage/history.py",
                    ("SITE_APPEND", "shared_policy().call(")),
    # heartbeat writes are best-effort by design: the boundary is the
    # monitor loop's exception tolerance, marked in parallel/health.py
    "SITE_HEARTBEAT": ("parallel/health.py",
                       ("SITE_HEARTBEAT", "fault_point(")),
    # the preemption probe's boundary is the sub-checkpoint ledger:
    # the sampler flushes and raises Preempted instead of dying dirty
    "SITE_PREEMPT": ("sampler/vectorized.py",
                     ("SITE_PREEMPT", "checkpointer")),
    # deposit's boundary: the manifest record hits the journal before
    # the deposit is acknowledged
    "SITE_STORE_DEPOSIT": ("wire/store.py",
                           ("SITE_STORE_DEPOSIT", "append_manifest(")),
    "SITE_STORE_SPILL": ("wire/store.py",
                         ("SITE_STORE_SPILL", "shared_policy().call(")),
    # hydrate's boundary: the content digest is verified on every host
    # decode, and the History runs the recovery ladder on mismatch
    "SITE_STORE_HYDRATE": ("wire/store.py",
                           ("SITE_STORE_HYDRATE", "verify_wire(")),
    "SITE_MATERIALIZE": ("storage/history.py",
                         ("SITE_MATERIALIZE", "shared_policy().call(")),
    "SITE_JOURNAL": ("resilience/journal.py",
                     ("SITE_JOURNAL", "shared_policy().call(")),
    # the one-dispatch egress drain's boundary: a drain failure latches
    # the degradation flag and the run resumes on the per-block paths
    # from the last durably-appended generation
    "SITE_DRAIN": ("smc.py",
                   ("SITE_DRAIN", "_fault_onedispatch_off")),
    # the continuous-batching window boundary: every retired lane's
    # summary is published durably (_cb_publish_lane) BEFORE the fault
    # point fires, so a kill between windows loses nothing published
    # and in-flight lanes bounce whole via the scheduler's lease requeue
    "SITE_SERVE_WINDOW": ("serve/worker.py",
                          ("SITE_SERVE_WINDOW", "_cb_publish_lane(")),
    # the fidelity calibrator's boundary: calibration rings are carry
    # state seeded BETWEEN durable generations, and a restart that
    # finds no ring reseeds NaN rings — the first screened generation
    # then self-disables (threshold +inf), so a kill here loses nothing
    "SITE_FIDELITY_CALIBRATE": ("smc.py",
                                ("SITE_FIDELITY_CALIBRATE",
                                 "_fidelity_nan_seed")),
}

_CONST_RE = re.compile(r'^(SITE_[A-Z_]+)\s*=\s*"([^"]+)"', re.M)

DOCS = "docs/resilience.md"
CHAOS = "tools/chaos_soak.py"

#: where a "no MANIFEST entry" finding points, now that the manifest
#: lives here (the predecessor script pointed at itself)
SELF = "tools/lint/rules/fault_sites.py"


def _repo_root(root: str = None) -> str:
    if root is not None:
        return root
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.dirname(os.path.dirname(os.path.dirname(here)))


def _read(path: str) -> str:
    with open(path, encoding="utf-8") as f:
        return f.read()


def site_constants(faults_text: str) -> dict:
    """``{constant_name: site_string}`` parsed from faults.py source."""
    return dict(_CONST_RE.findall(faults_text))


def check(root: str = None) -> list:
    """Returns ``[(where, message), ...]`` violations (empty = clean).
    ``root`` is the REPO root (this rule spans pyabc_tpu/, tests/,
    tools/ and docs/)."""
    root = _repo_root(root)
    pkg = os.path.join(root, "pyabc_tpu")
    violations = []

    faults_path = os.path.join(pkg, "resilience", "faults.py")
    if not os.path.exists(faults_path):
        return [("pyabc_tpu/resilience/faults.py", "missing")]
    faults_text = _read(faults_path)
    consts = site_constants(faults_text)

    # 1. completeness: constants <-> SITES tuple, statically.  Every
    # constant must be NAMED inside the SITES = (...) expression.
    m = re.search(r"^SITES\s*=\s*\(([^)]*)\)", faults_text, re.M)
    sites_body = m.group(1) if m else ""
    listed = set(re.findall(r"SITE_[A-Z_]+", sites_body))
    for name in consts:
        if name not in listed:
            violations.append((
                "pyabc_tpu/resilience/faults.py",
                f"{name} is defined but missing from SITES"))
    for name in listed - set(consts):
        violations.append((
            "pyabc_tpu/resilience/faults.py",
            f"SITES references undefined constant {name}"))

    # 2. planting + recovery boundary
    for name, site in consts.items():
        if name not in MANIFEST:
            violations.append((
                SELF,
                f"new site {name} ({site!r}) has no MANIFEST entry — "
                f"declare its planting file and recovery boundary"))
            continue
        rel, markers = MANIFEST[name]
        path = os.path.join(pkg, rel.replace("/", os.sep))
        if not os.path.exists(path):
            continue  # planted-tree tests cover subsets
        text = _read(path)
        for marker in markers:
            if marker not in text:
                violations.append((
                    f"pyabc_tpu/{rel}",
                    f"site {site!r}: expected marker {marker!r} not "
                    f"found (fault plant or its recovery boundary is "
                    f"gone)"))

    # 3. test coverage: the literal site string in tests/ or the chaos
    # harness (tier-1 runs its deterministic subset)
    test_dir = os.path.join(root, "tests")
    corpus = []
    if os.path.isdir(test_dir):
        for fn in sorted(os.listdir(test_dir)):
            if fn.endswith(".py"):
                corpus.append(_read(os.path.join(test_dir, fn)))
    chaos_path = os.path.join(root, CHAOS.replace("/", os.sep))
    if os.path.exists(chaos_path):
        corpus.append(_read(chaos_path))
    if corpus:
        blob = "\n".join(corpus)
        for name, site in consts.items():
            if site not in blob:
                violations.append((
                    "tests/", f"site {site!r} is exercised by no test "
                              f"(and absent from {CHAOS})"))

    # 4. docs: the site x action matrix must list every site
    docs_path = os.path.join(root, DOCS.replace("/", os.sep))
    if os.path.exists(docs_path):
        docs_text = _read(docs_path)
        for name, site in consts.items():
            if site not in docs_text:
                violations.append((
                    DOCS, f"site {site!r} missing from the fault-site "
                          f"matrix"))

    return violations


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    root = argv[0] if argv else None
    violations = check(root)
    if not violations:
        print("fault sites: clean (every site planted inside a "
              "recovery boundary, tested, and documented)")
        return 0
    print("fault-site violations:")
    for where, message in violations:
        print(f"  {where}: {message}")
    return 1


@register
class FaultSitesRule(Rule):
    id = "fault-sites"
    description = ("every chaos fault site is planted inside a recovery "
                   "boundary, tested, and documented")

    def run(self, tree):
        return [Finding(self.id, where, 0, message)
                for where, message in check(tree.repo_root)]
