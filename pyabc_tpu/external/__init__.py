"""External / black-box simulator bridges (parity: pyabc/external/)."""

from .base import (
    ExternalDistance,
    ExternalHandler,
    ExternalModel,
    ExternalSumStat,
    HostFunctionModel,
    R,
    create_sum_stat,
)

__all__ = ["ExternalHandler", "ExternalModel", "ExternalSumStat",
           "ExternalDistance", "HostFunctionModel", "R", "create_sum_stat"]
