"""Compile-once, run-many: the compilation-and-tuning layer.

Steady-state SMC generations should execute **zero XLA compiles after
generation 1**.  Three pieces make that hold (docs/performance.md
"Compilation & autotuning"):

- :mod:`.cache` — opt-in persistent XLA compilation cache
  (``PYABC_TPU_COMPILE_CACHE`` / ``ABCSMC(compile_cache=...)``), so
  ladder programs survive process restarts;
- :mod:`.ladder` — :class:`CompiledLadder`, the bounded thread-safe LRU
  of compiled rung programs shared by the vectorized/sharded samplers
  and the fused generation blocks, with background AOT prewarm of
  predicted rungs and the ``xla_*`` compile-event accounting;
- :mod:`.tuner` — :class:`BatchAutotuner`, the closed-loop batch-size
  policy fed by the telemetry timeline (acceptance rate + variance,
  undershoot rounds, compute/overlap seconds).

``jit_compile`` is the sanctioned ``jax.jit`` spelling for
per-generation code paths (``tools/check_no_inline_jit.py``).
"""

from __future__ import annotations

from .cache import COMPILE_CACHE_ENV, configure_compile_cache
from .ladder import (
    AotGuard,
    CompiledLadder,
    aot_compile,
    aval_of,
    avals_like,
    compile_counters,
    compile_delta,
    install_compile_listener,
    jit_compile,
)
from .tuner import BatchAutotuner

__all__ = [
    "AotGuard", "BatchAutotuner", "COMPILE_CACHE_ENV", "CompiledLadder",
    "aot_compile", "aval_of", "avals_like", "compile_counters",
    "compile_delta", "configure_compile_cache",
    "install_compile_listener", "jit_compile",
]
