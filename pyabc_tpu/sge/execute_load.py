"""Per-task entry point for SGE array jobs.

Parity: pyabc/sge/execute_load.py — unpickle function + argument, run it
inside the execution context, pickle the result, update the job DB.
Invoked as ``python -m pyabc_tpu.sge.execute_load <tmp_dir> <task_id>``.
"""

from __future__ import annotations

import os
import pickle
import sys


def main(tmp_dir: str, task_id: int):
    from .db import JobDB

    db = JobDB(tmp_dir)
    db.start(task_id)
    ok = False
    try:
        with open(os.path.join(tmp_dir, "function.pickle"), "rb") as f:
            bundle = pickle.load(f)
        function = bundle["function"]
        context_cls = bundle["context"]
        with open(os.path.join(tmp_dir, "jobs", f"{task_id}.job"),
                  "rb") as f:
            arg = pickle.load(f)
        with context_cls(tmp_dir, task_id):
            result = function(arg)
        ok = True
    except Exception as e:  # result file carries the exception
        result = e
    with open(os.path.join(tmp_dir, "results", f"{task_id}.result"),
              "wb") as f:
        pickle.dump(result, f)
    db.finish(task_id, ok)


if __name__ == "__main__":
    main(sys.argv[1], int(sys.argv[2]))
