"""Planted event-discipline violations: queue transitions that move a
ticket between states without emitting (or delegating toward) a
lifecycle trace event — each one is a hole in the study trace."""

import os
import time


class SilentQueue:
    def submit(self, spec):
        # a submission nobody will ever see in the trace
        path = os.path.join("pending", f"{spec.digest}.json")
        with open(path, "w") as f:
            f.write("{}")
        return path

    def requeue(self, ticket, worker=None, error=None):
        # the bounce vanishes: fold_phases charges the whole second
        # wait to the first queue_wait segment
        dest = os.path.join("pending", f"{ticket.id}.json")
        os.rename(ticket.path, dest)
        ticket.path = dest
        return True

    def _move(self, ticket, state, extra):
        payload = dict(extra)
        payload["moved_unix"] = time.time()
        dest = os.path.join(state, f"{ticket.id}.json")
        os.rename(ticket.path, dest)
        return dest
