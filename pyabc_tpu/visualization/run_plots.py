"""Run-trajectory plots: epsilons, sample numbers, acceptance rates, model
probabilities, ESS, credible intervals, histograms.

Parity map to pyabc/visualization/:
- ``plot_epsilons``              <- epsilon.py:11
- ``plot_sample_numbers``        <- sample.py:10-120
- ``plot_total_sample_numbers``  <- sample.py:88-171
- ``plot_sample_numbers_trajectory`` <- sample.py:174-255
- ``plot_acceptance_rates_trajectory`` <- sample.py:258-347
- ``plot_model_probabilities``   <- model_probabilities.py:6
- ``plot_effective_sample_sizes``<- effective_sample_size.py:11
- ``plot_credible_intervals``    <- credible.py:12-174
- ``plot_credible_intervals_for_time`` <- credible.py:177-353
- ``compute_credible_interval/compute_quantile/compute_kde_max``
                                 <- credible.py:356-397
- ``plot_histogram_1d/2d/matrix`` (+ ``_lowlevel``) <- histogram.py:8-253
- ``plot_data_callback`` (+ ``_lowlevel``) <- data.py:13-78
- ``plot_data_default``          <- data.py:81-175
"""

from __future__ import annotations

from typing import Callable, List, Optional

import numpy as np

from ..weighted_statistics import effective_sample_size, weighted_quantile


def _axes(ax):
    import matplotlib.pyplot as plt
    if ax is None:
        _, ax = plt.subplots()
    return ax


def _histories(histories):
    return histories if isinstance(histories, (list, tuple)) else [histories]


def plot_epsilons(histories, labels: Optional[List[str]] = None, ax=None,
                  scale: str = "log"):
    ax = _axes(ax)
    for i, h in enumerate(_histories(histories)):
        pops = h.get_all_populations()
        pops = pops[pops.t >= 0]
        label = labels[i] if labels else f"run {h.id}"
        ax.plot(pops.t, pops.epsilon, "x-", label=label)
    if scale == "log":
        ax.set_yscale("log")
    ax.set_xlabel("Population index t")
    ax.set_ylabel("Epsilon")
    ax.legend()
    return ax


def plot_sample_numbers(histories, labels=None, ax=None, rotation: int = 0):
    ax = _axes(ax)
    for i, h in enumerate(_histories(histories)):
        pops = h.get_all_populations()
        pops = pops[pops.t >= 0]
        label = labels[i] if labels else f"run {h.id}"
        ax.bar(pops.t + i * 0.2, pops.samples, width=0.2, label=label)
    ax.set_xlabel("Population index t")
    ax.set_ylabel("Samples")
    ax.legend()
    return ax


def plot_total_sample_numbers(histories, labels=None, ax=None):
    ax = _axes(ax)
    hs = _histories(histories)
    totals = [h.get_all_populations().samples.sum() for h in hs]
    names = labels or [f"run {h.id}" for h in hs]
    ax.bar(names, totals)
    ax.set_ylabel("Total samples")
    return ax


def plot_sample_numbers_trajectory(histories, labels=None, ax=None,
                                   yscale: str = "log",
                                   rotation: int = 0):
    """Required-samples trajectory over generations (sample.py:174-255)."""
    ax = _axes(ax)
    for i, h in enumerate(_histories(histories)):
        pops = h.get_all_populations()
        pops = pops[pops.t >= 0]
        label = labels[i] if labels else f"run {h.id}"
        ax.plot(pops.t, pops.samples, "x-", label=label)
    ax.set_yscale(yscale)
    ax.set_xlabel("Population index t")
    ax.set_ylabel("Samples")
    ax.tick_params(axis="x", rotation=rotation)
    ax.legend()
    return ax


def plot_acceptance_rates_trajectory(histories, labels=None, ax=None):
    ax = _axes(ax)
    for i, h in enumerate(_histories(histories)):
        pops = h.get_all_populations()
        pops = pops[pops.t >= 0]
        n_particles = h.get_nr_particles_per_population()
        rates = [n_particles.get(t, 0) / s if s else np.nan
                 for t, s in zip(pops.t, pops.samples)]
        label = labels[i] if labels else f"run {h.id}"
        ax.plot(pops.t, rates, "x-", label=label)
    ax.set_xlabel("Population index t")
    ax.set_ylabel("Acceptance rate")
    ax.legend()
    return ax


def plot_model_probabilities(history, ax=None):
    ax = _axes(ax)
    probs = history.get_model_probabilities()
    probs.plot.bar(ax=ax)
    ax.set_ylabel("Model probability")
    return ax


def plot_effective_sample_sizes(histories, labels=None, ax=None):
    ax = _axes(ax)
    for i, h in enumerate(_histories(histories)):
        ts, esss = [], []
        for t in range(h.max_t + 1):
            df = h.get_weighted_distances(t)
            if len(df):
                ts.append(t)
                esss.append(float(effective_sample_size(df["w"].to_numpy())))
        label = labels[i] if labels else f"run {h.id}"
        ax.plot(ts, esss, "x-", label=label)
    ax.set_xlabel("Population index t")
    ax.set_ylabel("ESS")
    ax.legend()
    return ax


def plot_credible_intervals(history, m: int = 0, par_names=None,
                            levels=(0.95,), show_mean: bool = True,
                            axes=None):
    """Per-generation credible-interval trajectories (credible.py:12-392)."""
    import matplotlib.pyplot as plt

    df0, _ = history.get_distribution(m=m)
    par_names = par_names or list(df0.columns)
    n = len(par_names)
    if axes is None:
        _, axes = plt.subplots(n, 1, figsize=(6, 2.5 * n), squeeze=False)
        axes = axes[:, 0]
    for k, par in enumerate(par_names):
        ax = axes[k]
        ts = list(range(history.max_t + 1))
        for level in levels:
            lows, highs = [], []
            for t in ts:
                df, w = history.get_distribution(m=m, t=t)
                vals = df[par].to_numpy()
                lows.append(float(weighted_quantile(
                    vals, w, alpha=(1 - level) / 2)))
                highs.append(float(weighted_quantile(
                    vals, w, alpha=1 - (1 - level) / 2)))
            ax.fill_between(ts, lows, highs, alpha=0.3,
                            label=f"{level:.0%} CI")
        if show_mean:
            means = []
            for t in ts:
                df, w = history.get_distribution(m=m, t=t)
                means.append(float(np.sum(df[par].to_numpy() * w)))
            ax.plot(ts, means, "x-", label="mean")
        ax.set_xlabel("Population index t")
        ax.set_ylabel(par)
        ax.legend()
    return axes


def compute_quantile(vals, weights, alpha: float) -> float:
    """Weighted quantile (credible.py:387-397)."""
    return float(weighted_quantile(np.asarray(vals), np.asarray(weights),
                                   alpha=alpha))


def compute_credible_interval(vals, weights, confidence: float = 0.95):
    """(lower, upper) weighted credible interval (credible.py:356-373)."""
    lb = compute_quantile(vals, weights, (1 - confidence) / 2)
    ub = compute_quantile(vals, weights, 1 - (1 - confidence) / 2)
    return lb, ub


def compute_kde_max(kde, df, w) -> np.ndarray:
    """Posterior mode: the KDE's density maximum over the sample support
    (credible.py:376-384 evaluates the fitted KDE at the sample points)."""
    import jax.numpy as jnp
    vals = df.to_numpy()
    kde.fit(jnp.asarray(vals, dtype=jnp.float32),
            jnp.asarray(np.asarray(w), dtype=jnp.float32))
    dens = np.asarray(kde.pdf(jnp.asarray(vals, dtype=jnp.float32)))
    return vals[int(np.argmax(dens))]


def plot_credible_intervals_for_time(histories, labels=None, ms=None,
                                     ts=None, par_names=None,
                                     levels=(0.95,), show_mean: bool = False,
                                     show_kde_max: bool = False,
                                     refvals=None, kde=None, axes=None,
                                     rotation: int = 0):
    """Credible intervals of several runs side by side at one time point
    each (credible.py:177-353): one subplot per parameter, one x position
    per history, nested error bars per confidence level."""
    import matplotlib.pyplot as plt

    hs = _histories(histories)
    n_run = len(hs)
    labels = labels or [f"run {h.id}" for h in hs]
    ms = ms if isinstance(ms, (list, tuple)) else [ms or 0] * n_run
    ts = ts if isinstance(ts, (list, tuple)) else \
        [h.max_t if ts is None else ts for h in hs]
    if refvals is not None and not isinstance(refvals, list):
        refvals = [refvals] * n_run
    if par_names is None:
        df0, _ = hs[0].get_distribution(m=ms[0], t=ts[0])
        par_names = list(df0.columns)
    levels = sorted(levels)
    n_par = len(par_names)
    if axes is None:
        _, axes = plt.subplots(n_par, 1, figsize=(6, 2.5 * n_par),
                               squeeze=False)
        axes = axes[:, 0]
    xs = np.arange(n_run)
    # one DB read (and at most one KDE fit) per history, not per parameter
    dists = [h.get_distribution(m=m, t=t) for h, m, t in zip(hs, ms, ts)]
    modes = None
    if show_kde_max:
        from ..transition import MultivariateNormalTransition
        modes = [compute_kde_max(kde or MultivariateNormalTransition(),
                                 df, w) for df, w in dists]
    for k, par in enumerate(par_names):
        ax = axes[k]
        for i, (df, w) in enumerate(dists):
            vals = df[par].to_numpy()
            median = compute_quantile(vals, w, 0.5)
            for li, level in enumerate(levels):
                lb, ub = compute_credible_interval(vals, w, level)
                ax.errorbar(x=[i], y=[median],
                            yerr=[[median - lb], [ub - median]],
                            capsize=10 / (li + 1), color=f"C{li}")
            if show_mean:
                ax.plot([i], [float(np.sum(vals * w))], "x", color="C6")
            if modes is not None:
                ax.plot([i], [modes[i][list(df.columns).index(par)]], "+",
                        color="C7")
            if refvals is not None and par in refvals[i]:
                ax.plot([i], [refvals[i][par]], "o", color="C4",
                        fillstyle="none")
        ax.set_xticks(xs)
        ax.set_xticklabels(labels, rotation=rotation)
        ax.set_ylabel(par)
    return axes


# ---------------------------------------------------------------------------
# histograms (histogram.py:8-253): highlevel takes a History, lowlevel arrays
# ---------------------------------------------------------------------------

def plot_histogram_1d_lowlevel(vals, weights=None, bins: int = 50, ax=None,
                               xname: str = "", refval=None, **kwargs):
    """histogram.py:49-84."""
    ax = _axes(ax)
    ax.hist(np.asarray(vals), weights=weights, bins=bins, density=True,
            **kwargs)
    if refval is not None:
        ax.axvline(refval, color="C1", linestyle="dotted")
    ax.set_xlabel(xname)
    ax.set_ylabel("Posterior")
    return ax


def plot_histogram_2d_lowlevel(xvals, yvals, weights=None, bins: int = 50,
                               ax=None, xname: str = "", yname: str = "",
                               refval=None, **kwargs):
    """histogram.py:128-169."""
    ax = _axes(ax)
    ax.hist2d(np.asarray(xvals), np.asarray(yvals), weights=weights,
              bins=bins, **kwargs)
    if refval is not None:
        ax.scatter([refval[0]], [refval[1]], color="C1", marker="x")
    ax.set_xlabel(xname)
    ax.set_ylabel(yname)
    return ax


def _dist_args(obj, w_or_x, args, kwargs):
    """Dispatch highlevel (History, x[, y], m=, t=) vs lowlevel-style
    (df, w, x[, y]) first arguments, returning (df, w, names)."""
    if hasattr(obj, "get_distribution"):  # History
        m = kwargs.pop("m", 0)
        t = kwargs.pop("t", None)
        df, w = obj.get_distribution(m=m, t=t)
        names = [w_or_x, *args]
        return df, w, names
    names = list(args)
    return obj, w_or_x, names


def plot_histogram_1d(obj, w_or_x, *args, bins: int = 50, ax=None,
                      refval=None, **kwargs):
    """Weighted 1D marginal histogram (histogram.py:8-46).

    Accepts the reference's highlevel form ``(history, x, m=..., t=...)``
    or array form ``(df, w, x)``.
    """
    df, w, names = _dist_args(obj, w_or_x, args, kwargs)
    x = names[0]
    return plot_histogram_1d_lowlevel(
        df[x].to_numpy(), w, bins=bins, ax=ax, xname=x,
        refval=refval[x] if refval else None, **kwargs)


def plot_histogram_2d(obj, w_or_x, *args, bins: int = 50, ax=None,
                      refval=None, **kwargs):
    """Weighted 2D histogram (histogram.py:87-125); highlevel form
    ``(history, x, y, m=..., t=...)`` or array form ``(df, w, x, y)``."""
    df, w, names = _dist_args(obj, w_or_x, args, kwargs)
    x, y = names[0], names[1]
    return plot_histogram_2d_lowlevel(
        df[x].to_numpy(), df[y].to_numpy(), w, bins=bins, ax=ax,
        xname=x, yname=y,
        refval=(refval[x], refval[y]) if refval else None, **kwargs)


def plot_histogram_matrix_lowlevel(df, w=None, bins: int = 50, refval=None,
                                   **kwargs):
    """histogram.py:206-253: hist 1d on the diagonal, scatter off it."""
    import matplotlib.pyplot as plt

    names = list(df.columns)
    n = len(names)
    fig, axes = plt.subplots(n, n, figsize=(2.5 * n, 2.5 * n),
                             squeeze=False)
    for i, yi in enumerate(names):
        for j, xj in enumerate(names):
            ax = axes[i][j]
            if i == j:
                plot_histogram_1d_lowlevel(
                    df[xj].to_numpy(), w, bins=bins, ax=ax, xname=xj,
                    refval=refval[xj] if refval else None)
            else:
                ax.scatter(df[xj].to_numpy(), df[yi].to_numpy(),
                           s=4, alpha=0.5)
                if refval is not None:
                    ax.scatter([refval[xj]], [refval[yi]], color="C1",
                               marker="x")
                ax.set_xlabel(xj)
                ax.set_ylabel(yi)
    fig.tight_layout()
    return axes


def plot_histogram_matrix(history, m: int = 0, t=None, bins: int = 50,
                          refval=None, **kwargs):
    """histogram.py:172-203."""
    df, w = history.get_distribution(m=m, t=t)
    return plot_histogram_matrix_lowlevel(df, w, bins=bins, refval=refval,
                                          **kwargs)


# ---------------------------------------------------------------------------
# data plots (data.py:13-175)
# ---------------------------------------------------------------------------

def plot_data_callback_lowlevel(sum_stats: List, weights,
                                f_plot: Optional[Callable] = None,
                                f_plot_aggregated: Optional[Callable] = None,
                                ax=None, **kwargs):
    """data.py:50-78: ``f_plot(sum_stat, weight, ax, **kw)`` per particle,
    ``f_plot_aggregated(sum_stats, weights, ax, **kw)`` once."""
    ax = _axes(ax)
    if f_plot is not None:
        for sum_stat, weight in zip(sum_stats, weights):
            f_plot(sum_stat, weight, ax, **kwargs)
    if f_plot_aggregated is not None:
        f_plot_aggregated(sum_stats, weights, ax, **kwargs)
    return ax


def plot_data_callback(history, f_plot: Optional[Callable] = None,
                       f_plot_aggregated: Optional[Callable] = None,
                       t=None, n: Optional[int] = None, ax=None, **kwargs):
    """Plot stored sum-stats via callbacks (data.py:13-47). ``n`` bounds
    how many particles are drawn (extension: the reference draws all)."""
    weights, sum_stats = history.get_weighted_sum_stats(t=t)
    if n is not None and len(sum_stats) > n:
        idx = np.linspace(0, len(sum_stats) - 1, n).astype(int)
        sum_stats = [sum_stats[i] for i in idx]
        weights = weights[idx]
    return plot_data_callback_lowlevel(
        sum_stats, weights, f_plot, f_plot_aggregated, ax, **kwargs)


def plot_data_default(obs_data: dict, sim_data: dict, keys=None):
    """Default observed-vs-simulated grid (data.py:81-175): line plot for
    1d values, coordinate scatter for 2d, DataFrame columns supported."""
    import matplotlib.pyplot as plt
    import pandas as pd

    if keys is None:
        keys = list(obs_data.keys())
    if not isinstance(keys, list):
        keys = [keys]
    obs_data = {k: obs_data[k] for k in keys}
    sim_data = {k: sim_data[k] for k in keys}
    ndata = len(obs_data)
    ncols = int(np.ceil(np.sqrt(ndata)))
    nrows = ncols
    while ncols * (nrows - 1) >= ndata:
        nrows -= 1
    fig, arr_ax = plt.subplots(nrows, ncols, squeeze=False)
    flat_axes = arr_ax.flatten()
    for idx, key in enumerate(keys):
        ax = flat_axes[idx]
        obs, sim = obs_data[key], sim_data[key]
        if isinstance(obs, pd.DataFrame):
            if len(obs.columns) == 1:
                ax.plot(np.asarray(sim).flatten(), "-x", label="Simulation")
                ax.plot(np.asarray(obs).flatten(), "-x", label="Data")
                ax.set_xlabel("Index")
                ax.set_ylabel(obs.columns[0])
            else:
                for col in obs.columns:
                    ax.scatter(obs[col].to_numpy(), sim[col].to_numpy(),
                               label=col)
                ax.set_xlabel("Data")
                ax.set_ylabel("Simulation")
        else:
            obs = np.atleast_1d(np.asarray(obs))
            sim = np.atleast_1d(np.asarray(sim))
            if obs.ndim == 1:
                ax.plot(sim, "-x", color="C0", label="Simulation")
                ax.plot(obs, "-x", color="C1", label="Data")
                ax.set_xlabel("Index")
                ax.set_ylabel(str(key))
            else:
                for j, (ov, sv) in enumerate(zip(obs, sim)):
                    ax.scatter(ov, sv, label=f"Coordinate {j}")
                ax.set_xlabel("Data")
                ax.set_ylabel("Simulation")
        ax.set_title(str(key))
        ax.legend()
    for ax in flat_axes[ndata:]:
        ax.axis("off")
    fig.tight_layout()
    return arr_ax
