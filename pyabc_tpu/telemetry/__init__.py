"""Unified observability for pyabc_tpu: span tracing, a typed metrics
registry, and the per-generation run timeline.

- :mod:`.spans` — Chrome-trace-emitting span tracer (``span("gen.sample",
  gen=t)``), enabled by ``PYABC_TPU_TRACE`` or ``ABCSMC(trace_path=...)``.
- :mod:`.metrics` — counter/gauge/histogram registry backing the wire
  transfer ledger and the sampler counters; Prometheus-text export via
  the ``abc-distributed-manager metrics`` CLI.
- :mod:`.timeline` — :class:`~pyabc_tpu.telemetry.timeline.GenerationTimeline`
  fed by the orchestrator at generation boundaries.
- :func:`profile_generation` — optional ``jax.profiler`` hook for a
  single generation (``PYABC_TPU_PROFILE_GEN=<t>``).
- :mod:`.aggregate` — cross-host fleet layer over the shared run
  directory: per-host snapshot/span publishing, the clock-aligned
  merged trace, sum/max/p50/p99 rollups and the fleet Prometheus
  endpoint (``abc-top`` / ``abc-server`` read through it).
- :mod:`.flight` — always-on bounded flight recorder dumping
  ``flight_<runid>.json`` on crash / ``RetryExhausted`` / SIGTERM.

See docs/observability.md for the operator guide.
"""

from __future__ import annotations

import contextlib
import os

from . import aggregate, flight, metrics, spans, timeline
from .flight import RECORDER
from .metrics import REGISTRY
from .spans import TRACER, begin, end, span
from .timeline import GenerationTimeline

#: generation index to wrap in a device profiler trace (unset = off)
PROFILE_GEN_ENV = "PYABC_TPU_PROFILE_GEN"
#: where the profiler writes its trace directory
PROFILE_DIR_ENV = "PYABC_TPU_PROFILE_DIR"


@contextlib.contextmanager
def profile_generation(t: int):
    """Wrap generation ``t`` in a ``jax.profiler.trace`` when
    ``PYABC_TPU_PROFILE_GEN`` names it; otherwise free (one env lookup).

    The trace directory defaults to ``/tmp/pyabc_tpu_profile`` and is
    overridable via ``PYABC_TPU_PROFILE_DIR``; view with TensorBoard's
    profile plugin or ``xprof``.
    """
    want = os.environ.get(PROFILE_GEN_ENV)
    if want is None or str(t) != want:
        yield
        return
    import jax

    log_dir = os.environ.get(PROFILE_DIR_ENV, "/tmp/pyabc_tpu_profile")
    with jax.profiler.trace(log_dir):
        yield


__all__ = [
    "GenerationTimeline", "RECORDER", "REGISTRY", "TRACER", "aggregate",
    "begin", "end", "flight", "metrics", "profile_generation", "span",
    "spans", "timeline",
]
