"""Device-resident population store (pyabc_tpu/wire/store.py) and lazy
History hydration (storage/history.py).

The tentpole contract: in ``history_mode="lazy"`` accepted populations
stay parked on device in a bounded ring and steady-state egress is an
O(KB) posterior summary packet — yet every consumer (transition fits,
History queries, resumed runs) sees populations BIT-IDENTICAL to the
eager dataflow, because hydration replays the exact production decode
path.  These tests pin:

- codec round-trips are bit-identical for every dtype/shape class
  (wire/transfer.py PTW1 delta+zlib container);
- the ring's deposit/evict/spill/drop/manifest accounting;
- eager-vs-lazy posterior bit-identity on the sequential, fused and
  pipelined run paths (np.array_equal, not allclose);
- eviction pressure (ring capacity 1) degrades to the durable-DB
  fallback without changing a single bit;
- steady-state population-bucket egress does not grow with generations
  under lazy mode while eager grows >= 10x faster;
- the resilience ledger's manifest-only rows + the preemption flush
  anchor (persist_lazy_tail) survive a store-backed run.
"""

import json

import numpy as np
import pytest

import pyabc_tpu as pt
from pyabc_tpu.models import make_two_gaussians_problem
from pyabc_tpu.wire import store as wire_store
from pyabc_tpu.wire import transfer


# ---------------------------------------------------------------------------
# codec: PTW1 container round-trips bit-identically
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("codec", ["delta", "raw"])
def test_codec_roundtrip_bit_identity(codec):
    """Every dtype the wire ships must survive encode/decode with the
    exact bit pattern — including NaN/Inf payloads and shapes the delta
    transform cannot help (0-d, single row)."""
    rng = np.random.default_rng(0)
    arrays = [
        np.float16(rng.normal(size=(64, 3)) * 100),
        np.float32(rng.normal(size=(257,))),
        np.float64(rng.normal(size=(33, 2, 2))),
        rng.integers(-128, 127, size=(65,), dtype=np.int8),
        rng.integers(0, 2 ** 31, size=(12, 5)).astype(np.int32),
        rng.integers(0, 2 ** 16, size=(40,), dtype=np.uint16),
        (rng.random(50) < 0.5),                      # bool
        np.array(3.25, dtype=np.float32),            # 0-d -> plain
        np.float32(rng.normal(size=(1, 7))),         # single row -> plain
        np.zeros((0, 4), dtype=np.float32),          # empty
    ]
    special = np.float32(rng.normal(size=(20, 2)))
    special[3, 0] = np.nan
    special[7, 1] = np.inf
    special[11, 0] = -np.inf
    special[0, 0] = -0.0
    arrays.append(special)
    for arr in arrays:
        blob = transfer.encode_array(arr, codec=codec)
        assert bytes(blob[:4]) == b"PTW1"
        out = transfer.decode_array(blob)
        assert out.dtype == arr.dtype
        assert out.shape == arr.shape
        assert out.tobytes() == arr.tobytes()  # bit-identity, not ==


def test_codec_rejects_garbage():
    with pytest.raises(ValueError):
        transfer.decode_array(b"nope" + b"\0" * 16)
    with pytest.raises(ValueError):
        transfer.encode_array(np.array([object()]))


def test_codec_delta_actually_compresses_correlated_rows():
    """Round-ordered accepted rows correlate; the delta codec must beat
    the raw container on them (the reason it exists)."""
    base = np.linspace(0.0, 1.0, 4096, dtype=np.float32)
    arr = (base[:, None] + np.float32(1e-4) * np.arange(3)).astype(
        np.float32)
    delta = transfer.encode_array(arr, codec="delta")
    raw = transfer.encode_array(arr, codec="raw")
    assert len(delta) < len(raw)
    assert transfer.decode_array(delta).tobytes() == arr.tobytes()


# ---------------------------------------------------------------------------
# ring mechanics
# ---------------------------------------------------------------------------

def _dummy_wire(t):
    import jax.numpy as jnp
    return {"theta": jnp.full((8, 2), float(t)),
            "m": jnp.zeros((8,), jnp.int32)}


def test_store_ring_eviction_spill_and_drop():
    store = wire_store.DeviceRunStore(max_gens=2)
    for t in range(3):
        store.deposit(t, _dummy_wire(t), n=8, count=8, eps=1.0 - t * 0.1,
                      norm="stream")
    # ring holds the newest two; the oldest moved to the spill queue
    assert store.resident_ts() == [1, 2]
    assert store.deposits == 3 and store.evictions == 1
    spills = store.take_spills()
    assert [e["t"] for e in spills] == [0]
    assert store.take_spills() == []  # drained

    meta = store.entry_meta(2)
    assert meta["n"] == 8 and meta["count"] == 8
    assert meta["norm"] == "stream" and meta["nbytes"] > 0
    assert store.entry_meta(0) is None

    # re-deposit of a resident t replaces, not duplicates
    store.deposit(2, _dummy_wire(2), n=8, count=4, norm="stream")
    assert store.resident_ts() == [1, 2]
    assert store.entry_meta(2)["count"] == 4

    assert store.drop(1) and not store.drop(1)
    assert store.resident_ts() == [2]


def test_store_drop_from_covers_spills():
    """Pipelined rewind: speculative generations past the frontier must
    vanish from the ring AND the spill queue."""
    store = wire_store.DeviceRunStore(max_gens=2)
    for t in range(4):
        store.deposit(t, _dummy_wire(t), n=8, count=8, norm="stream")
    assert store.resident_ts() == [2, 3]
    assert sorted(store.manifest()["spill_pending"]) == [0, 1]
    dropped = store.drop_from(1)
    assert dropped == 3  # gens 1 (spill), 2, 3 (resident)
    assert store.resident_ts() == []
    assert [e["t"] for e in store.take_spills()] == [0]


def test_store_manifest_snapshot():
    store = wire_store.DeviceRunStore(max_gens=4)
    store.deposit(5, _dummy_wire(5), n=8, count=7, eps=0.25, norm="sample")
    man = store.manifest()
    assert man["max_gens"] == 4 and man["deposits"] == 1
    (entry,) = man["resident"]
    assert entry["t"] == 5 and entry["count"] == 7
    assert entry["eps"] == 0.25 and entry["norm"] == "sample"
    json.dumps(man)  # ledger row must be JSON-able


# ---------------------------------------------------------------------------
# eager-vs-lazy posterior bit-identity (the tentpole acceptance gate)
# ---------------------------------------------------------------------------

def _run(mode, pop=256, gens=4, seed=7, db="sqlite://", **kw):
    models, priors, distance, observed, _ = make_two_gaussians_problem()
    abc = pt.ABCSMC(models, priors, distance, population_size=pop,
                    sampler=pt.VectorizedSampler(), seed=seed,
                    history_mode=mode, **kw)
    abc.new(db, observed)
    abc.run(max_nr_populations=gens)
    return abc


def _assert_bit_identical(h_e, h_l, label):
    assert h_e.max_t == h_l.max_t
    for t in range(h_e.max_t + 1):
        for m in range(2):
            de, we = h_e.get_distribution(m, t)
            dl, wl = h_l.get_distribution(m, t)
            assert np.array_equal(np.asarray(de["mu"]),
                                  np.asarray(dl["mu"])), \
                f"{label}: theta differs at t={t} m={m}"
            assert np.array_equal(we, wl), \
                f"{label}: weights differ at t={t} m={m}"
        pe = h_e.get_population(t=t)
        pl = h_l.get_population(t=t)
        assert np.array_equal(np.asarray(pe.distance),
                              np.asarray(pl.distance))


def test_sequential_lazy_bit_identical_and_summary_row():
    abc_e = _run("eager", ingest_mode="sequential")
    abc_l = _run("lazy", ingest_mode="sequential")
    _assert_bit_identical(abc_e.history, abc_l.history, "sequential")
    # the lazy append left an O(KB) posterior packet on every row ...
    for t in range(abc_l.history.max_t + 1):
        packet = abc_l.history.get_population_summary(t)
        assert packet is not None
        assert packet["ess"] > 0
        assert np.isclose(sum(packet["model_w"]), 1.0)
        assert len(packet["mean"]) == 1  # one shared mu axis
    # ... eager rows have none, and the timeline records the mode
    assert abc_e.history.get_population_summary(0) is None
    assert abc_l.timeline.summary()["history_mode"] == "lazy"
    assert abc_e.timeline.summary()["history_mode"] == "eager"


def test_fused_lazy_bit_identical(db_path):
    abc_e = _run("eager", fuse_generations=3, ingest_mode="sequential")
    abc_l = _run("lazy", fuse_generations=3, ingest_mode="sequential",
                 db="sqlite:///" + db_path)
    _assert_bit_identical(abc_e.history, abc_l.history, "fused")
    # a fresh History on the same file sees the same bits (the durable
    # fallback every resumed/offline reader takes)
    h2 = pt.History("sqlite:///" + db_path, abc_id=abc_l.history.id)
    _assert_bit_identical(abc_e.history, h2, "fused/reload")


def test_pipelined_lazy_bit_identical():
    abc_e = _run("eager", fuse_generations=2, ingest_mode="overlap")
    abc_l = _run("lazy", fuse_generations=2, ingest_mode="overlap")
    _assert_bit_identical(abc_e.history, abc_l.history, "pipelined")


@pytest.mark.slow
def test_lazy_bit_identical_pop1e4():
    """The ISSUE acceptance gate at the specified scale."""
    abc_e = _run("eager", pop=10_000, gens=4, fuse_generations=3,
                 ingest_mode="sequential")
    abc_l = _run("lazy", pop=10_000, gens=4, fuse_generations=3,
                 ingest_mode="sequential")
    _assert_bit_identical(abc_e.history, abc_l.history, "pop1e4")


def test_eviction_pressure_falls_back_bit_identically(monkeypatch):
    """Ring capacity 1 under a 3-generation fused block: every block
    spills two generations to the durable queue mid-flight — results
    must not change by a bit."""
    monkeypatch.setenv(wire_store.STORE_GENS_ENV, "1")
    abc_l = _run("lazy", fuse_generations=3, ingest_mode="sequential")
    monkeypatch.delenv(wire_store.STORE_GENS_ENV)
    abc_e = _run("eager", fuse_generations=3, ingest_mode="sequential")
    _assert_bit_identical(abc_e.history, abc_l.history, "evicted")


def test_env_default_and_validation(monkeypatch):
    models, priors, distance, observed, _ = make_two_gaussians_problem()
    monkeypatch.setenv(wire_store.HISTORY_MODE_ENV, "eager")
    abc = pt.ABCSMC(models, priors, distance, population_size=64)
    assert abc.history_mode == "eager"
    monkeypatch.delenv(wire_store.HISTORY_MODE_ENV)
    abc = pt.ABCSMC(models, priors, distance, population_size=64)
    assert abc.history_mode == "lazy"  # the PR's default
    with pytest.raises(ValueError, match="history_mode"):
        pt.ABCSMC(models, priors, distance, population_size=64,
                  history_mode="nope")


# ---------------------------------------------------------------------------
# steady-state egress: the wire is dead
# ---------------------------------------------------------------------------

def test_steady_state_population_egress_ratio(monkeypatch):
    """Per-generation growth of the population egress bucket: eager
    ships the full accepted population every generation; lazy ships
    summary packets (population growth ZERO after calibration).  The
    contract is >= 10x; measured growth under lazy is 0 bytes/gen."""
    monkeypatch.setenv("PYABC_TPU_LAZY_FINAL_ONLY", "1")

    def growth(mode):
        per_run = []
        for gens in (2, 5):
            b0 = dict(transfer.egress_breakdown())
            _run(mode, pop=512, gens=gens, fuse_generations=3,
                 ingest_mode="sequential")
            b1 = transfer.egress_breakdown()
            per_run.append({k: b1[k] - b0.get(k, 0) for k in b1})
        short, long_ = per_run
        return {k: (long_[k] - short[k]) / 3.0 for k in long_}

    eager = growth("eager")
    lazy = growth("lazy")
    assert eager["population"] > 0
    ratio = eager["population"] / max(lazy["population"], 1.0)
    assert ratio >= 10, (eager, lazy)
    # the generations still talk — in O(KB) summary packets
    assert 0 < lazy["summary"] < eager["population"] / 10
    # hydrated fetches book egress("history"), never population
    assert lazy["history"] >= 0


def test_egress_sum_invariant_holds_in_lazy_mode():
    """Every byte still lands in exactly one bucket when the store
    re-routes population traffic (the fleet-telemetry invariant must
    survive the new labels)."""
    from pyabc_tpu.telemetry import REGISTRY
    total_key = "wire_d2h_bytes_total"
    t0 = REGISTRY.to_dict().get(total_key, 0)
    b0 = dict(transfer.egress_breakdown())
    _run("lazy", pop=256, gens=3, fuse_generations=3,
         ingest_mode="sequential")
    delta_total = REGISTRY.to_dict().get(total_key, 0) - t0
    b1 = transfer.egress_breakdown()
    delta_sum = sum(b1[k] - b0.get(k, 0) for k in b1)
    assert delta_total > 0
    assert delta_sum == delta_total


# ---------------------------------------------------------------------------
# resilience: manifest-only ledger rows + the preemption anchor
# ---------------------------------------------------------------------------

def test_manifest_flush_and_preemption_anchor(db_path):
    """Steady-state ledger flushes in lazy mode are manifest-only (zero
    raw bytes); an actual preemption persists the device-resident tail
    newest-first and raises Preempted with a durable resume anchor."""
    from pyabc_tpu.resilience import checkpoint as ckpt

    models, priors, distance, observed, _ = make_two_gaussians_problem()
    abc = pt.ABCSMC(models, priors, distance, population_size=256,
                    sampler=pt.VectorizedSampler(), seed=11,
                    history_mode="lazy", ingest_mode="sequential")
    h = abc.new("sqlite:///" + db_path, observed)
    abc.run(max_nr_populations=2)

    store = abc._store
    assert store is not None
    # park a synthetic ledger: cadence flush with a live manifest source
    ck = ckpt.GenCheckpointer(h, t=9, every_rounds=1, eps=0.5)
    ck.manifest_source = store.manifest
    assert not ck.raw_required()
    ck.flush_manifest(rounds=3, nr_evaluations=1000)
    assert h.load_sub_checkpoint(9) is None  # no raw rows to splice
    man = h.load_sub_checkpoint_manifest(9)
    assert man is not None and man["max_gens"] >= 1

    # preemption: raw becomes required and the lazy tail goes durable
    ckpt.clear_preempt()
    ckpt.request_preempt()
    try:
        assert ck.raw_required()
        with pytest.raises(ckpt.Preempted):
            ck.maybe_raise_preempted()
    finally:
        ckpt.clear_preempt()
    # persist_lazy_tail ran: nothing summary-only is left to purge, and
    # a resumed process anchors on the full run
    h2 = pt.History("sqlite:///" + db_path, abc_id=h.id)
    h2.purge_stale_lazy()
    assert h2.max_t == h.max_t
    h.clear_sub_checkpoint(9)


def test_resume_purges_unhydratable_summary_rows(db_path):
    """A lazy row whose device store died with its process cannot be
    hydrated; ABCSMC.load must purge it so max_t anchors on durable
    blobs and the run regenerates from there."""
    models, priors, distance, observed, _ = make_two_gaussians_problem()
    abc = pt.ABCSMC(models, priors, distance, population_size=128,
                    sampler=pt.VectorizedSampler(), seed=3,
                    history_mode="lazy", ingest_mode="sequential")
    h = abc.new("sqlite:///" + db_path, observed)
    abc.run(max_nr_populations=2)
    max_t = h.max_t
    # forge the crash artifact: a summary-only row for a generation
    # whose wire never left the (now dead) device
    h._conn.execute(
        "INSERT INTO populations (abc_smc_id, t, epsilon, nr_samples,"
        " population_end_time, lazy, summary) VALUES (?,?,?,?,?,1,?)",
        (h.id, max_t + 1, 0.1, 999, "x",
         json.dumps({"ess": 1.0, "model_w": [1.0]})))
    h._conn.commit()
    assert h.max_t == max_t + 1

    abc2 = pt.ABCSMC(models, priors, distance, population_size=128,
                     sampler=pt.VectorizedSampler(), seed=4,
                     history_mode="lazy", ingest_mode="sequential")
    h2 = abc2.load("sqlite:///" + db_path)
    assert h2.max_t == max_t  # stale summary row purged on load
