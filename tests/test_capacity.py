"""The HBM capacity model, the at-rest carry codec, and budget-clamped
planning (capacity/ tentpole; docs/performance.md "The HBM ladder").

Pins, in order: the byte arithmetic of every ledger column against
hand-computed values (three engines, every lane toggle), budget
resolution, the plan search order (exactness first: geometry clamps
before the precision ladder narrows), the completability constraint
(a clamped geometry must still be able to FILL the population within
its round budget), the full CapacityError payload incl. the precision
hint, the bf16/int8 carry codec (round-trip, idempotence, aux-key
layout, determinism), the occupancy tuner's capacity clamp (a tight
budget shrinks the rung instead of OOMing), end-to-end runs where an
f32 plan provably cannot fit but the auto ladder completes compressed,
f32 bit-identity with the env unset, and — in the slow battery — the
4-seed posterior gate of the bf16 carry on SIR and Lotka-Volterra.
"""

import math

import jax.numpy as jnp
import numpy as np
import pytest

import pyabc_tpu as pt
from pyabc_tpu.autotune.occupancy import OccupancyTuner
from pyabc_tpu.capacity import (
    ROUND_HEADROOM,
    CapacityError,
    ledger,
    parse_bytes,
    plan,
    predict_peak_bytes,
    resolved_budget_bytes,
)
from pyabc_tpu.models import (
    make_lotka_volterra_problem,
    make_sir_problem,
    make_two_gaussians_problem,
)
from pyabc_tpu.ops.precision import (
    CARRY_COMPRESSED_LANES,
    decode_carry,
    encode_carry,
    resolve_carry_precision,
)


@pytest.fixture(autouse=True)
def _clean_capacity_env(monkeypatch):
    """No capacity/codec knob may leak between tests — the carry mode
    enters compile-cache keys and the budget changes plan results."""
    for var in ("PYABC_TPU_HBM_BUDGET", "PYABC_TPU_HBM_HEADROOM",
                "PYABC_TPU_CARRY_PRECISION",
                "PYABC_TPU_CAPACITY_MEASURE"):
        monkeypatch.delenv(var, raising=False)
    yield


# ---------------------------------------------------------------------------
# parse_bytes / budget resolution
# ---------------------------------------------------------------------------

def test_parse_bytes():
    assert parse_bytes("12G") == 12 * 1024 ** 3
    assert parse_bytes("900M") == 900 * 1024 ** 2
    assert parse_bytes("64k") == 64 * 1024
    assert parse_bytes("2T") == 2 * 1024 ** 4
    assert parse_bytes("1.5G") == int(1.5 * 1024 ** 3)
    assert parse_bytes("2GiB") == 2 * 1024 ** 3
    assert parse_bytes("512mb") == 512 * 1024 ** 2
    assert parse_bytes("123") == 123
    assert parse_bytes(4096) == 4096
    assert parse_bytes(2.5) == 2
    assert parse_bytes("") == 0
    with pytest.raises(ValueError, match="PYABC_TPU_HBM_BUDGET"):
        parse_bytes("12 gigs")


def test_resolved_budget_env_verbatim(monkeypatch):
    monkeypatch.setenv("PYABC_TPU_HBM_BUDGET", "2M")
    assert resolved_budget_bytes() == 2 * 1024 ** 2


def test_resolved_budget_cpu_is_unconstrained():
    # CPU backends report no bytes_limit: budget 0, every plan fits
    assert resolved_budget_bytes() == 0


# ---------------------------------------------------------------------------
# ledger arithmetic (hand-computed bytes)
# ---------------------------------------------------------------------------

_SHAPE = dict(population=1000, param_dim=2, stat_dim=4, batch=256,
              K=3, max_T=32)


def test_ledger_fused_f32_hand_computed():
    led = ledger(engine="fused", carry_precision="f32", **_SHAPE)
    # carry row: m(4) + log_weight(4) + 4*(d + 1 + s) = 36 bytes
    assert led["carry_at_rest"] == 1000 * 36
    # accept window: (n + B) rows at the full f32 promotion width
    assert led["accept_window"] == (1000 + 256) * 36
    # round workspace: B * 4 * (d + s + 3) * sim_mult(4)
    assert led["round_batch"] == 256 * 4 * 9 * 4
    # K wire slots of f16 lanes: 2d + 3 per row
    assert led["wire_egress"] == 3 * 1000 * 7
    # refit support: models * n * (4d + 8), NOT device-divided
    assert led["refit_support"] == 1000 * 16
    assert led["record_ring"] == 0
    assert led["fidelity_rings"] == 0
    assert led["telemetry"] == 0
    assert predict_peak_bytes(
        engine="fused", carry_precision="f32", **_SHAPE) == \
        sum(led.values())


def test_ledger_onedispatch_slots_are_max_t():
    led = ledger(engine="onedispatch", carry_precision="f32", **_SHAPE)
    assert led["wire_egress"] == 32 * 1000 * 7
    # every other column matches the fused layout
    fused = ledger(engine="fused", carry_precision="f32", **_SHAPE)
    for col in led:
        if col != "wire_egress":
            assert led[col] == fused[col]


def test_ledger_sequential_double_buffers_and_forces_f32():
    led = ledger(engine="sequential", carry_precision="bf16", **_SHAPE)
    # the host loop re-uploads per generation (x2) and never stores a
    # compressed carry: the bf16 request reads as f32
    assert led["carry_at_rest"] == 2 * 1000 * 36
    assert led["wire_egress"] == 0


def test_ledger_precision_narrows_only_the_bulk():
    bf16 = ledger(engine="fused", carry_precision="bf16", **_SHAPE)
    int8 = ledger(engine="fused", carry_precision="int8", **_SHAPE)
    # bulk row at width w: 4 + 4 + w * (d + 1 + s)
    assert bf16["carry_at_rest"] == 1000 * (8 + 2 * 7)
    assert int8["carry_at_rest"] == 1000 * (8 + 1 * 7)
    # the accept window is the f32 promotion width — incompressible
    f32 = ledger(engine="fused", carry_precision="f32", **_SHAPE)
    assert bf16["accept_window"] == f32["accept_window"]
    assert int8["accept_window"] == f32["accept_window"]


def test_ledger_lane_toggles():
    base = ledger(engine="fused", carry_precision="f32", **_SHAPE)
    no_donate = ledger(engine="fused", carry_precision="f32",
                       donate=False, **_SHAPE)
    assert no_donate["carry_at_rest"] == 2 * base["carry_at_rest"]
    tel = ledger(engine="fused", carry_precision="f32",
                 telemetry_lanes=True, **_SHAPE)
    assert tel["telemetry"] == 4096
    ws = ledger(engine="fused", carry_precision="f32", wire_stats=True,
                **_SHAPE)
    assert ws["wire_egress"] == 3 * 1000 * (7 + 2 * 4)
    m3 = ledger(engine="fused", carry_precision="f32", models=3,
                **_SHAPE)
    assert m3["refit_support"] == 3 * base["refit_support"]
    capped = ledger(engine="fused", carry_precision="f32",
                    support_cap=100, **_SHAPE)
    assert capped["refit_support"] == 100 * 16
    rr = ledger(engine="fused", carry_precision="f32", record_rows=10,
                **_SHAPE)
    assert rr["record_ring"] == 10 * (4 * 2 + 16)
    cal = ledger(engine="fused", carry_precision="f32", cal_rows=5,
                 **_SHAPE)
    assert cal["fidelity_rings"] == 2 * 5 * 8


def test_ledger_devices_divide_population_not_support():
    led = ledger(engine="fused", carry_precision="f32", devices=4,
                 **_SHAPE)
    assert led["carry_at_rest"] == 250 * 36
    assert led["accept_window"] == (250 + 64) * 36
    assert led["round_batch"] == 64 * 4 * 9 * 4
    assert led["wire_egress"] == 3 * 250 * 7
    # refit support rows are replicated per device for the KDE
    # cross-product — never divided
    assert led["refit_support"] == 1000 * 16


def test_ledger_rejects_auto_and_unknown_engine():
    with pytest.raises(ValueError, match="concrete carry_precision"):
        ledger(engine="fused", carry_precision="auto", **_SHAPE)
    with pytest.raises(ValueError, match="unknown engine"):
        ledger(engine="warp", carry_precision="f32", **_SHAPE)


# ---------------------------------------------------------------------------
# plan(): search order, clamping, completability, CapacityError
# ---------------------------------------------------------------------------

_PLAN_KW = dict(population=4096, param_dim=2, stat_dim=4,
                engine="onedispatch")


def _mins(**overrides):
    """Per-precision completable minima via the 1-byte-budget probe —
    the same protocol the podstar_pop1e8 bench workers use."""
    out = {}
    for prec in ("f32", "bf16"):
        kw = dict(_PLAN_KW, batch=8192, K=4, max_T=32, budget=1,
                  carry_precision=prec)
        kw.update(overrides)
        with pytest.raises(CapacityError) as ei:
            plan(**kw)
        out[prec] = int(ei.value.predicted)
    return out


def test_plan_unconstrained_returns_request_verbatim():
    p = plan(batch=8192, K=4, max_T=32, carry_precision="auto",
             budget=0, **_PLAN_KW)
    assert (p.carry_precision, p.batch, p.K, p.max_T) == \
        ("f32", 8192, 4, 32)
    assert p.note == "unconstrained"
    assert p.budget_bytes == 0


def test_plan_fits_as_requested_under_generous_budget():
    p = plan(batch=8192, K=4, max_T=32, carry_precision="f32",
             budget=10 ** 12, **_PLAN_KW)
    assert (p.batch, p.K, p.max_T) == (8192, 4, 32)
    assert p.note == "fits as requested"
    assert p.predicted_bytes == predict_peak_bytes(
        batch=8192, K=4, max_T=32, carry_precision="f32", **_PLAN_KW)


def test_plan_clamps_geometry_before_narrowing_precision():
    full = predict_peak_bytes(batch=8192, K=4, max_T=32,
                              carry_precision="f32", **_PLAN_KW)
    p = plan(batch=8192, K=4, max_T=32, carry_precision="auto",
             budget=full - 1, **_PLAN_KW)
    # exactness first: the budget only just excludes the requested
    # geometry, so a smaller f32 point must win before bf16 is tried
    assert p.carry_precision == "f32"
    assert p.note == "clamped to fit budget"
    assert (p.batch, p.K, p.max_T) != (8192, 4, 32)
    assert p.predicted_bytes <= full - 1


def test_plan_auto_descends_to_bf16_at_discriminating_budget():
    mins = _mins()
    assert 0 < mins["bf16"] < mins["f32"]
    budget = (mins["f32"] + mins["bf16"]) // 2
    p = plan(batch=8192, K=4, max_T=32, carry_precision="auto",
             budget=budget, **_PLAN_KW)
    assert p.carry_precision == "bf16"
    assert p.note == "clamped to fit budget"
    assert p.predicted_bytes <= budget


def test_plan_never_emits_an_uncompletable_geometry():
    mins = _mins()
    budget = (mins["f32"] + mins["bf16"]) // 2
    p = plan(batch=8192, K=4, max_T=32, carry_precision="auto",
             budget=budget, **_PLAN_KW)
    need = math.ceil(ROUND_HEADROOM * _PLAN_KW["population"] / p.batch)
    assert need <= p.max_T


def test_plan_raises_when_no_geometry_can_fill_the_population():
    # batch rungs floor at min(batch, 256): no (256, <=8) point can
    # propose 4x the population, whatever the byte budget
    with pytest.raises(CapacityError, match="can fill population"):
        plan(population=100_000, param_dim=2, stat_dim=4,
             engine="onedispatch", batch=256, K=1, max_T=8,
             budget=10 ** 12, carry_precision="f32")


def test_capacity_error_payload_and_hint():
    mins = _mins()
    budget = (mins["f32"] + mins["bf16"]) // 2
    with pytest.raises(CapacityError) as ei:
        plan(batch=8192, K=4, max_T=32, carry_precision="f32",
             budget=budget, **_PLAN_KW)
    err = ei.value
    assert err.budget == budget
    assert err.predicted == mins["f32"]
    assert err.request["carry_precision"] == "f32"
    assert err.request["engine"] == "onedispatch"
    assert set(err.ledger) == {
        "carry_at_rest", "accept_window", "round_batch", "wire_egress",
        "refit_support", "record_ring", "fidelity_rings", "telemetry"}
    assert "PYABC_TPU_CARRY_PRECISION=bf16 would fit" in err.hint
    # the rendered message carries the ledger and the hint
    assert "carry_at_rest" in str(err)
    assert "hint:" in str(err)


def test_plan_snaps_rungs_through_the_sampler_rounder():
    mins = _mins()
    budget = (mins["f32"] + mins["bf16"]) // 2

    def rounder(b):
        return max((int(b) // 512) * 512, 512)

    p = plan(batch=8192, K=4, max_T=32, carry_precision="auto",
             budget=budget * 2, round_to_batch=rounder, **_PLAN_KW)
    assert p.batch % 512 == 0


# ---------------------------------------------------------------------------
# the at-rest carry codec
# ---------------------------------------------------------------------------

def _carry(n=64, d=3, s=5, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "m": jnp.asarray(rng.integers(0, 2, n), jnp.int32),
        "log_weight": jnp.asarray(rng.normal(size=n), jnp.float32),
        "theta": jnp.asarray(rng.normal(size=(n, d)) * 10.0,
                             jnp.float32),
        "distance": jnp.asarray(rng.uniform(0.0, 5.0, n), jnp.float32),
        "stats": jnp.asarray(rng.normal(size=(n, s)), jnp.float32),
        "count": jnp.int32(n),
    }


def test_codec_f32_is_identity_same_object():
    c = _carry()
    assert encode_carry(c, "f32") is c
    assert decode_carry(c, "f32") is c


def test_codec_bf16_round_trip_and_untouched_lanes():
    c = _carry()
    enc = encode_carry(c, "bf16")
    for k in CARRY_COMPRESSED_LANES:
        assert enc[k].dtype == jnp.bfloat16
    # accumulator lanes never narrow — same objects, no new ops
    assert enc["m"] is c["m"]
    assert enc["log_weight"] is c["log_weight"]
    assert enc["count"] is c["count"]
    dec = decode_carry(enc, "bf16")
    for k in CARRY_COMPRESSED_LANES:
        assert dec[k].dtype == jnp.float32
        expect = np.asarray(c[k]).astype(jnp.bfloat16).astype(np.float32)
        assert np.array_equal(np.asarray(dec[k]), expect)
    # idempotent: an already-encoded lane passes through untouched
    assert encode_carry(enc, "bf16")["theta"] is enc["theta"]
    assert decode_carry(dec, "bf16")["theta"] is dec["theta"]


def test_codec_int8_aux_keys_and_error_bound():
    c = _carry()
    enc = encode_carry(c, "int8")
    for k in CARRY_COMPRESSED_LANES:
        assert enc[k].dtype == jnp.int8
        # flat per-column aux (NOT population-sized, so the pod
        # sharding pin leaves them replicated)
        assert enc[k + "_qs"].dtype == jnp.float32
        assert enc[k + "_qs"].shape == np.asarray(c[k]).shape[1:]
        assert enc[k + "_qm"].shape == np.asarray(c[k]).shape[1:]
    dec = decode_carry(enc, "int8")
    for k in CARRY_COMPRESSED_LANES:
        assert k + "_qs" not in dec and k + "_qm" not in dec
        x = np.asarray(c[k], np.float64)
        span = x.max(axis=0) - x.min(axis=0)
        err = np.abs(np.asarray(dec[k], np.float64) - x)
        # affine 255-level grid: error bounded by one step
        assert np.all(err <= span / 254.0 + 1e-6)
    # idempotent re-encode keeps the quantized lanes and aux as-is
    enc2 = encode_carry(enc, "int8")
    assert enc2["theta"] is enc["theta"]
    assert enc2["theta_qs"] is enc["theta_qs"]


def test_codec_int8_clamps_non_finite_to_column_floor():
    c = _carry()
    theta = np.asarray(c["theta"]).copy()
    theta[3, 1] = np.inf
    c["theta"] = jnp.asarray(theta)
    dec = decode_carry(encode_carry(c, "int8"), "int8")
    out = np.asarray(dec["theta"])
    assert np.all(np.isfinite(out))
    finite_lo = theta[np.isfinite(theta[:, 1]), 1].min()
    assert out[3, 1] == pytest.approx(finite_lo, abs=1e-5)


def test_codec_is_deterministic():
    for mode in ("bf16", "int8"):
        a = encode_carry(_carry(seed=7), mode)
        b = encode_carry(_carry(seed=7), mode)
        for k in a:
            assert np.array_equal(np.asarray(a[k]), np.asarray(b[k])), \
                (mode, k)


def test_codec_rejects_unknown_modes():
    with pytest.raises(ValueError, match="bad mode"):
        encode_carry(_carry(), "f16")
    with pytest.raises(ValueError, match="bad mode"):
        decode_carry(_carry(), "f64")


def test_resolve_carry_precision(monkeypatch):
    assert resolve_carry_precision() == "f32"
    monkeypatch.setenv("PYABC_TPU_CARRY_PRECISION", "bf16")
    assert resolve_carry_precision() == "bf16"  # re-read, never cached
    assert resolve_carry_precision("int8") == "int8"  # arg wins
    with pytest.raises(ValueError, match="PYABC_TPU_CARRY_PRECISION"):
        resolve_carry_precision("fp8")


# ---------------------------------------------------------------------------
# occupancy tuner: capacity clamp (a tight budget shrinks the rung)
# ---------------------------------------------------------------------------

def _pow2_rung(b):
    return max(256, 1 << int(round(math.log2(max(float(b), 1.0)))))


def test_occupancy_fallback_shrinks_rung_to_feasible_set():
    tuner = OccupancyTuner(k_max=4)
    K, max_T, B = tuner.propose(
        n=8192, rate=0.5, B0=4096, round_to_rung=_pow2_rung,
        feasible=lambda K, T, B: B <= 1024)
    # no scored candidate fits (rungs explored: 2048/4096/8192), so the
    # fallback clamps through shrinking rungs instead of returning a
    # shape the device would OOM on
    assert (K, max_T, B) == (1, tuner.t_choices[-1], 1024)


def test_occupancy_scores_only_inside_the_feasible_set():
    tuner = OccupancyTuner(k_max=4)
    # telemetry so scoring has real rho/timing estimates
    tuner.observe_block(K=2, B=4096, rounds_per_gen=[4, 6],
                        wall_s=1.0, written=2)
    K, max_T, B = tuner.propose(
        n=8192, rate=0.5, B0=4096, round_to_rung=_pow2_rung,
        feasible=lambda K, T, B: B <= 2048)
    assert B == 2048
    K2, _, B2 = tuner.propose(
        n=8192, rate=0.5, B0=4096, round_to_rung=_pow2_rung)
    assert B2 in (2048, 4096, 8192)  # unclamped search unchanged


# ---------------------------------------------------------------------------
# end-to-end: budget-clamped runs on the fused engine
# ---------------------------------------------------------------------------

def _abc(pop=256, fuse=2, seed=0, **kwargs):
    models, priors, distance, observed, posterior_fn = \
        make_two_gaussians_problem()
    abc = pt.ABCSMC(models, priors, distance, population_size=pop,
                    eps=pt.ConstantEpsilon(0.3),
                    sampler=pt.VectorizedSampler(),
                    fuse_generations=fuse, seed=seed, **kwargs)
    abc.new("sqlite://", observed)
    return abc


#: one unconstrained default-config reference run, shared lazily by
#: the tests below (the autouse fixture guarantees a clean env at
#: every entry, so whichever test builds it first sees the default)
_REF = {}


def _ref_run():
    if not _REF:
        abc = _abc()
        h = abc.run(max_nr_populations=3)
        df, w = h.get_distribution(m=0)
        _REF["cap"] = dict(abc.timeline.capacity)
        _REF["dist"] = (df.to_numpy(), np.asarray(w))
    return _REF


def _fused_mins(abc, n):
    samp = abc.sampler
    B = samp.choose_batch(n)
    kw = abc._capacity_kwargs("fused", n, B)
    out = {}
    for prec in ("f32", "bf16"):
        with pytest.raises(CapacityError) as ei:
            plan(batch=B, K=abc.fuse_generations, max_T=32, budget=1,
                 carry_precision=prec, **kw)
        out[prec] = int(ei.value.predicted)
    return out


def test_tight_budget_clamps_rung_and_run_completes(monkeypatch):
    # unconstrained reference: what the consult would request
    cap_ref = _ref_run()["cap"]
    assert cap_ref["note"] == "unconstrained"
    # regression (occupancy satellite): one byte under the requested
    # geometry's footprint must shrink the shape, not OOM or bounce
    monkeypatch.setenv("PYABC_TPU_HBM_BUDGET",
                       str(cap_ref["predicted_bytes"] - 1))
    abc = _abc()
    h = abc.run(max_nr_populations=3)
    cap = abc.timeline.capacity
    assert cap["note"] == "clamped to fit budget"
    assert (cap["batch"], cap["K"], cap["max_T"]) != \
        (cap_ref["batch"], cap_ref["K"], cap_ref["max_T"])
    assert cap["predicted_bytes"] < cap_ref["predicted_bytes"]
    assert len(h.get_all_populations()) == 4  # prior + 3 generations


def test_f32_raises_where_auto_completes_compressed(monkeypatch):
    probe = _abc()
    mins = _fused_mins(probe, 256)
    assert 0 < mins["bf16"] < mins["f32"]
    budget = (mins["f32"] + mins["bf16"]) // 2
    monkeypatch.setenv("PYABC_TPU_HBM_BUDGET", str(budget))

    # pinned f32: no geometry fits — the error names the mode that would
    monkeypatch.setenv("PYABC_TPU_CARRY_PRECISION", "f32")
    with pytest.raises(CapacityError) as ei:
        _abc().run(max_nr_populations=3)
    assert "PYABC_TPU_CARRY_PRECISION=bf16" in (ei.value.hint or "")

    # auto: the planner narrows the carry and the run completes
    monkeypatch.setenv("PYABC_TPU_CARRY_PRECISION", "auto")
    abc = _abc()
    h = abc.run(max_nr_populations=3)
    assert abc.timeline.capacity["precision"] == "bf16"
    assert abc._carry_mode == "bf16"
    assert len(h.get_all_populations()) == 4


def test_f32_env_is_bit_identical_to_default(monkeypatch):
    df0, w0 = _ref_run()["dist"]
    monkeypatch.setenv("PYABC_TPU_CARRY_PRECISION", "f32")
    h1 = _abc().run(max_nr_populations=3)
    df1, w1 = h1.get_distribution(m=0)
    # the f32 codec is the same-object identity: explicit f32 must be
    # bit-for-bit the default program, not merely statistically close
    assert np.array_equal(df0, df1.to_numpy())
    assert np.array_equal(w0, np.asarray(w1))


@pytest.mark.parametrize("mode", ["bf16", "int8"])
def test_compressed_runs_complete_and_are_deterministic(
        mode, monkeypatch):
    monkeypatch.setenv("PYABC_TPU_CARRY_PRECISION", mode)
    dists = []
    for _ in range(2):
        h = _abc(seed=5).run(max_nr_populations=3)
        dists.append(h.get_distribution(m=0))
    (df0, w0), (df1, w1) = dists
    assert np.array_equal(df0.to_numpy(), df1.to_numpy())
    assert np.array_equal(np.asarray(w0), np.asarray(w1))


# ---------------------------------------------------------------------------
# slow battery: the 4-seed posterior gate of the bf16 carry
# ---------------------------------------------------------------------------

def _posterior_moments(problem_factory, pop, gens, seed, fuse=4):
    models, priors, distance, observed = problem_factory()
    abc = pt.ABCSMC(models, priors, distance, population_size=pop,
                    eps=pt.MedianEpsilon(), fuse_generations=fuse,
                    seed=seed)
    abc.new("sqlite://", observed)
    h = abc.run(max_nr_populations=gens)
    df, w = h.get_distribution(m=0)
    w = np.asarray(w, np.float64)
    cols = sorted(df.columns)
    x = np.stack([df[c].to_numpy(np.float64) for c in cols], axis=1)
    mean = w @ x
    std = np.sqrt(np.maximum(w @ (x - mean) ** 2, 1e-30))
    return mean, std


@pytest.mark.slow
@pytest.mark.parametrize("seed", [0, 1, 2, 3])
@pytest.mark.parametrize("problem", [make_sir_problem,
                                     make_lotka_volterra_problem],
                         ids=["sir", "lotka_volterra"])
def test_bf16_carry_posterior_gate(problem, seed, monkeypatch):
    """The compressed at-rest carry must leave the posterior intact:
    same problem, same seed, f32 vs bf16 carries — the per-parameter
    posterior means may differ only at Monte-Carlo scale (a fraction
    of the posterior spread), across 4 independent seeds on both the
    SIR tau-leap and the Lotka-Volterra SDE problems."""
    pop, gens = (2000, 6) if problem is make_sir_problem else (1000, 5)
    monkeypatch.setenv("PYABC_TPU_CARRY_PRECISION", "f32")
    mean_f32, std_f32 = _posterior_moments(problem, pop, gens, seed)
    monkeypatch.setenv("PYABC_TPU_CARRY_PRECISION", "bf16")
    mean_bf16, std_bf16 = _posterior_moments(problem, pop, gens, seed)
    scale = np.maximum(std_f32, 1e-3)
    assert np.all(np.abs(mean_bf16 - mean_f32) <= 0.5 * scale), (
        mean_f32, mean_bf16, std_f32)
    # the spread itself must not collapse or explode under compression
    assert np.all(std_bf16 <= 2.0 * std_f32 + 1e-3)
    assert np.all(std_bf16 >= 0.33 * std_f32 - 1e-3)
