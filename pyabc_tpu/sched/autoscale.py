"""Desired-replica targeting from queue depth and aging pressure.

The scheduler does not start workers itself (that is the operator's or
a wrapper script's job — a k8s HPA analog, ``kubectl scale``, or a
plain loop spawning ``abc-serve`` processes); it *emits a target*:
``sched_desired_replicas``, published in every scheduler snapshot and
printed by ``abc-sched``.  The raw target is capacity arithmetic —
enough workers to hold the current backlog at
``PYABC_TPU_SCHED_STUDIES_PER_WORKER`` studies each, plus one when the
oldest pending study has aged past
``PYABC_TPU_SCHED_AGING_PRESSURE_S`` (an aged queue means the fleet is
too small even when it is shallow) — clamped to
``[PYABC_TPU_SCHED_MIN_REPLICAS, PYABC_TPU_SCHED_MAX_REPLICAS]``.

The *published* target applies hysteresis in BOTH directions: the raw
target must hold strictly above the current value for
``PYABC_TPU_SCHED_UP_TICKS`` consecutive ticks before the target moves
up, and strictly below for ``PYABC_TPU_SCHED_DOWN_TICKS`` ticks before
it moves down.  Scale-down is deliberately slower than scale-up
(defaults 5 vs 2): killing a warm worker throws away its compiled
ladder, so a transient lull must not thrash the pool that took real
compile seconds to build.
"""

from __future__ import annotations

import math
import os
from typing import Optional

MIN_REPLICAS_ENV = "PYABC_TPU_SCHED_MIN_REPLICAS"
MAX_REPLICAS_ENV = "PYABC_TPU_SCHED_MAX_REPLICAS"
STUDIES_PER_WORKER_ENV = "PYABC_TPU_SCHED_STUDIES_PER_WORKER"
AGING_PRESSURE_ENV = "PYABC_TPU_SCHED_AGING_PRESSURE_S"
UP_TICKS_ENV = "PYABC_TPU_SCHED_UP_TICKS"
DOWN_TICKS_ENV = "PYABC_TPU_SCHED_DOWN_TICKS"

_DEFAULT_MIN_REPLICAS = 1
_DEFAULT_MAX_REPLICAS = 16
_DEFAULT_STUDIES_PER_WORKER = 8
_DEFAULT_AGING_PRESSURE_S = 120.0
_DEFAULT_UP_TICKS = 2
_DEFAULT_DOWN_TICKS = 5


def _env_int(name: str, default: int) -> int:
    try:
        return max(int(os.environ.get(name, str(default))), 1)
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return max(float(os.environ.get(name, str(default))), 1e-3)
    except ValueError:
        return default


class Autoscaler:
    """Hysteresis-filtered replica targeting (module docstring).

    Pure bookkeeping over the observations fed to :meth:`observe` —
    no filesystem, no clocks — so the hysteresis contract is unit
    testable tick by tick (``tests/test_sched.py``).
    """

    def __init__(self, min_replicas: Optional[int] = None,
                 max_replicas: Optional[int] = None,
                 studies_per_worker: Optional[int] = None,
                 aging_pressure_s: Optional[float] = None,
                 up_ticks: Optional[int] = None,
                 down_ticks: Optional[int] = None):
        self.min_replicas = (
            _env_int(MIN_REPLICAS_ENV, _DEFAULT_MIN_REPLICAS)
            if min_replicas is None else max(int(min_replicas), 0))
        self.max_replicas = max(
            _env_int(MAX_REPLICAS_ENV, _DEFAULT_MAX_REPLICAS)
            if max_replicas is None else int(max_replicas),
            self.min_replicas)
        self.studies_per_worker = (
            _env_int(STUDIES_PER_WORKER_ENV, _DEFAULT_STUDIES_PER_WORKER)
            if studies_per_worker is None else max(
                int(studies_per_worker), 1))
        self.aging_pressure_s = (
            _env_float(AGING_PRESSURE_ENV, _DEFAULT_AGING_PRESSURE_S)
            if aging_pressure_s is None else float(aging_pressure_s))
        self.up_ticks = (_env_int(UP_TICKS_ENV, _DEFAULT_UP_TICKS)
                         if up_ticks is None else max(int(up_ticks), 1))
        self.down_ticks = (
            _env_int(DOWN_TICKS_ENV, _DEFAULT_DOWN_TICKS)
            if down_ticks is None else max(int(down_ticks), 1))
        self.desired: Optional[int] = None
        self._up_streak = 0
        self._down_streak = 0

    def target(self, pending: int, claimed: int,
               oldest_pending_s: float = 0.0) -> int:
        """The raw (un-filtered) capacity target for this instant."""
        backlog = max(int(pending), 0) + max(int(claimed), 0)
        raw = math.ceil(backlog / self.studies_per_worker)
        if oldest_pending_s > self.aging_pressure_s:
            raw += 1  # aged queue: depth alone understates the need
        return min(max(raw, self.min_replicas), self.max_replicas)

    def observe(self, pending: int, claimed: int,
                oldest_pending_s: float = 0.0) -> int:
        """Feed one tick's queue observation; returns the
        hysteresis-filtered desired replica count.  The first
        observation seeds the target directly (there is no previous
        value to defend)."""
        raw = self.target(pending, claimed, oldest_pending_s)
        if self.desired is None:
            self.desired = raw
            return self.desired
        if raw > self.desired:
            self._up_streak += 1
            self._down_streak = 0
            if self._up_streak >= self.up_ticks:
                self.desired = raw
                self._up_streak = 0
        elif raw < self.desired:
            self._down_streak += 1
            self._up_streak = 0
            if self._down_streak >= self.down_ticks:
                self.desired = raw
                self._down_streak = 0
        else:
            self._up_streak = 0
            self._down_streak = 0
        return self.desired
