"""The claim-discipline_bad violations, silenced every sanctioned way:
settle-in-finally, claim-and-return handoff, the historical
``# claim-ok`` marker, and the generic graftlint allow."""


def serve_one(queue, worker_id):
    # the real contract: settle on every unwind path
    ticket = queue.claim(worker_id)
    if ticket is None:
        return None
    try:
        summary = run_study(ticket)
        queue.complete(ticket)
        return summary
    except Exception as exc:
        queue.requeue(ticket, error=repr(exc))
        raise


def claim_next(queue, worker_id):
    # claim-and-return helper: the caller owns settlement
    return queue.claim(worker_id)


def claim_for_janitor(queue, worker_id):
    # unwind story lives in a process-level janitor sweep
    ticket = queue.claim(worker_id)  # claim-ok
    return ticket.id if ticket else None


def claim_suppressed(queue, worker_id):
    ticket = queue.claim(worker_id)  # graftlint: allow(claim-discipline)
    return ticket.id if ticket else None


def drain(queue, worker_id):
    try:
        serve_one(queue, worker_id)
    finally:
        queue.requeue_worker(worker_id)


def run_study(ticket):
    return {"id": ticket.id}
