"""Rule ``precision-policy``: every MXU contraction in the numeric
kernels states its precision explicitly.

On TPU, an unannotated ``jnp.dot``/``jnp.matmul`` runs at XLA's
``Precision.DEFAULT`` — single-pass bf16, which injects O(0.1)
absolute error into a Mahalanobis exponent (measured; see
ops/kde.py).  Whether that is acceptable is a per-site NUMERICAL
decision, so the kernels must write it down: either a ``precision=``
kwarg (``HIGHEST`` for exact f32 passes) or
``preferred_element_type=`` (the bf16x3 split's f32 accumulators —
ops/precision.py).  The bare ``@`` operator cannot carry either, so
it is always flagged in scope.

Scope: ``ops/`` and ``distance/`` — the modules whose contractions
run inside compiled sampling programs.  AST-based: multi-line calls
annotate on any line; comments can't false-positive.

Suppression: ``# precision-ok`` on the reported line;
``# graftlint: allow(precision-policy)`` also works.
"""

from __future__ import annotations

import ast
import os
import sys

from ..core import Finding, Rule, default_package_root, dotted_name, register

#: numeric-kernel surface (package-root-relative, forward slashes)
SCAN_PREFIXES = ("ops/", "distance/")

SUPPRESS = "# precision-ok"

#: contraction callables that accept precision kwargs
_CONTRACTIONS = ("dot", "matmul", "einsum", "tensordot", "vdot")
#: module spellings whose contractions hit the MXU
_BASES = ("jnp", "jax.numpy")
_KWARGS = ("precision", "preferred_element_type")


def _package_root(root: str = None) -> str:
    return root if root is not None else default_package_root()


def _scan_source(rel: str, text: str) -> list:
    try:
        tree = ast.parse(text)
    except SyntaxError:
        return []  # the interpreter will complain louder than we can
    lines = text.splitlines()

    def line_of(node) -> str:
        lineno = getattr(node, "lineno", 0)
        return lines[lineno - 1] if 1 <= lineno <= len(lines) else ""

    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.MatMult):
            if SUPPRESS in line_of(node):
                continue
            out.append((rel, node.lineno,
                        "bare '@' matmul cannot state a precision — "
                        "spell it jnp.matmul(..., precision=...) or "
                        "use ops.precision.bf16x3_matmul"))
        elif isinstance(node, ast.Call):
            name = dotted_name(node.func)
            if name is None:
                continue
            base, _, attr = name.rpartition(".")
            if attr not in _CONTRACTIONS or base not in _BASES:
                continue
            if any(kw.arg in _KWARGS for kw in node.keywords):
                continue
            if SUPPRESS in line_of(node):
                continue
            out.append((rel, node.lineno,
                        f"{name}(...) without precision= or "
                        "preferred_element_type= runs at DEFAULT "
                        "(single-pass bf16) — state the lane"))
    return out


def check(root: str = None) -> list:
    """Scan the kernel surface; returns
    ``[(relpath, lineno, message), ...]`` violations (empty = clean)."""
    root = _package_root(root)
    violations = []
    for dirpath, _dirnames, filenames in os.walk(root):
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            if not rel.startswith(SCAN_PREFIXES):
                continue
            with open(path, encoding="utf-8") as f:
                violations.extend(_scan_source(rel, f.read()))
    violations.sort(key=lambda v: (v[0], v[1]))
    return violations


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    root = argv[0] if argv else None
    violations = check(root)
    if not violations:
        print("precision policy: clean (every kernel contraction "
              "states its lane)")
        return 0
    print("unannotated MXU contraction in ops//distance/ (add "
          "precision= or preferred_element_type=, or justify with "
          f"'{SUPPRESS}'):")
    for rel, lineno, msg in violations:
        print(f"  pyabc_tpu/{rel}:{lineno}: {msg}")
    return 1


@register
class PrecisionPolicyRule(Rule):
    id = "precision-policy"
    description = ("ops/ and distance/ contractions state precision= or "
                   "preferred_element_type= explicitly (no DEFAULT bf16)")

    def run(self, tree):
        prefix = tree.package_rel_prefix()
        return [Finding(self.id, f"{prefix}/{rel}", lineno, msg)
                for rel, lineno, msg in check(tree.package_root)]
