"""Two competing Gaussian models — the blessed model-selection problem.

Parity: the reference's central integration problem
``two_competing_gaussians_multiple_population``
(test/base/test_samplers.py:128-209): two models, y ~ N(x, σ²) with means
drawn from uniform priors; the analytic model posterior is checked in tests.
Also BASELINE config #2 (Gaussian mixture model selection at scale).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..distance import PNormDistance
from ..model import SimpleModel
from ..random_variables import RV, Distribution


def make_two_gaussians_problem(sigma: float = 0.5,
                               y_observed: float = 1.0,
                               mu_a: float = -0.5, mu_b: float = 0.5,
                               prior_width: float = 1.0):
    """Two models differing only in the prior location of their mean.

    Model j simulates y ~ N(mu, sigma²); prior of model A centers mu_a,
    model B centers mu_b (mirrors test_samplers.py:130-148).
    Returns (models, priors, distance, observed, posterior_fn) where
    ``posterior_fn(y)`` gives the analytic model-B posterior probability.
    """

    def sample_fn(key, theta):
        mu = theta[:, 0]
        return {"y": mu + sigma * jax.random.normal(key, mu.shape)}

    models = [SimpleModel(sample_fn, name="model_a"),
              SimpleModel(sample_fn, name="model_b")]
    priors = [Distribution(mu=RV("uniform", mu_a, prior_width)),
              Distribution(mu=RV("uniform", mu_b, prior_width))]
    distance = PNormDistance(p=2)
    observed = {"y": y_observed}

    def posterior_fn(y: float):
        """Analytic P(model B | y) under uniform model prior: marginal
        likelihood of y is the uniform-normal convolution
        (test_samplers.py:186-203 analog)."""
        from scipy import stats as ss

        def marginal(lo, width):
            # ∫ N(y; mu, sigma²) · U(mu; lo, lo+width) dmu
            return (ss.norm.cdf(y, lo, sigma)
                    - ss.norm.cdf(y, lo + width, sigma)) / width

        pa = marginal(mu_a, prior_width)
        pb = marginal(mu_b, prior_width)
        return pb / (pa + pb)

    return models, priors, distance, observed, posterior_fn
