"""Quickstart: infer the mean of a Gaussian from one observation.

The TPU edition of the reference's parameter-inference quickstart
notebook: a batched JAX simulator, a uniform prior, adaptive epsilon, and
a posterior read back from the SQLite history.

Run: ``python examples/quickstart.py`` (env var ABC_EXAMPLE_POP shrinks
the run for CI).
"""

import os

import jax
import numpy as np

import pyabc_tpu as pt

POP = int(os.environ.get("ABC_EXAMPLE_POP", 2000))
GENS = int(os.environ.get("ABC_EXAMPLE_GENS", 6))


def model(key, theta):
    """theta: [N, 1] — one simulated observation per particle."""
    noise = jax.random.normal(key, (theta.shape[0], 1)) * 0.1
    return {"y": theta[:, :1] + noise}


def main():
    abc = pt.ABCSMC(
        pt.SimpleModel(model),
        pt.Distribution(mu=pt.RV("uniform", -1.0, 2.0)),
        pt.PNormDistance(p=2),
        population_size=POP,
        seed=1)
    abc.new("sqlite://", {"y": 0.4})
    history = abc.run(max_nr_populations=GENS, minimum_epsilon=0.01)

    df, w = history.get_distribution()
    mu_mean = float(np.sum(df["mu"].to_numpy() * w))
    print(f"posterior mean of mu: {mu_mean:.3f} (true 0.4)")
    assert abs(mu_mean - 0.4) < 0.1
    return history


if __name__ == "__main__":
    main()
