"""Deprecated alias: the canonical transfer ledger lives in the wire
subsystem (``pyabc_tpu/wire/transfer.py``) since streaming ingest landed,
and its storage is now the telemetry metrics registry.  This module
re-exports the registry-backed API unchanged; import from
``pyabc_tpu.wire.transfer`` instead."""

import warnings

from ..wire.transfer import (  # noqa: F401
    _lock,
    _state,
    _tree_nbytes,
    delta,
    record_compute,
    record_d2h,
    record_decode,
    record_h2d,
    record_overlap,
    record_rewind,
    snapshot,
    timed_d2h,
)

warnings.warn(
    "pyabc_tpu.utils.transfer is deprecated; import "
    "pyabc_tpu.wire.transfer instead",
    DeprecationWarning,
    stacklevel=2,
)
