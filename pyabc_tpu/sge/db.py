"""Job-state DB for the SGE mapper.

Parity: pyabc/sge/db.py:13-144 — an sqlite file inside the job tmp dir
tracks per-task start/completion; the master polls it with timeout-based
re-waits (db.py:42).
"""

from __future__ import annotations

import os
import sqlite3
import time


class JobDB:
    def __init__(self, tmp_dir: str):
        self.path = os.path.join(tmp_dir, "jobs.db")

    def _conn(self):
        return sqlite3.connect(self.path, timeout=30)

    def create(self, n_tasks: int):
        with self._conn() as c:
            c.execute("CREATE TABLE IF NOT EXISTS tasks "
                      "(id INTEGER PRIMARY KEY, started REAL, finished REAL,"
                      " ok INTEGER)")
            c.executemany("INSERT INTO tasks VALUES (?, NULL, NULL, NULL)",
                          [(k,) for k in range(1, n_tasks + 1)])

    def start(self, task_id: int):
        with self._conn() as c:
            c.execute("UPDATE tasks SET started=? WHERE id=?",
                      (time.time(), task_id))

    def finish(self, task_id: int, ok: bool):
        with self._conn() as c:
            c.execute("UPDATE tasks SET finished=?, ok=? WHERE id=?",
                      (time.time(), int(ok), task_id))

    def n_unfinished(self) -> int:
        with self._conn() as c:
            row = c.execute("SELECT COUNT(*) FROM tasks WHERE finished IS "
                            "NULL").fetchone()
            return int(row[0])

    def wait_for_completion(self, poll_interval: float = 0.2,
                            timeout: float = 24 * 3600):
        t0 = time.time()
        while self.n_unfinished():
            if time.time() - t0 > timeout:
                raise TimeoutError("SGE jobs did not finish in time")
            time.sleep(poll_interval)
