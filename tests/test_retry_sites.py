"""Tier-1 wrapper for tools/check_retry_sites.py: every hot-loop device
dispatch must route through resilience/retry.py (self._dispatch /
self._retry.call), the d2h chokepoint must keep its retry wrapper, and
the lint must actually catch a violation when one is planted."""

import importlib.util
import os

_TOOL = os.path.join(os.path.dirname(__file__), os.pardir, "tools",
                     "check_retry_sites.py")


def _load():
    spec = importlib.util.spec_from_file_location(
        "check_retry_sites", _TOOL)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_repo_tree_is_clean():
    """No raw stateful-loop/block dispatch outside the retry wrappers —
    the invariant that makes transient-failure absorption total."""
    mod = _load()
    assert mod.check() == []


def test_detects_planted_violations(tmp_path):
    mod = _load()
    pkg = tmp_path / "pkg"
    (pkg / "sampler").mkdir(parents=True)
    (pkg / "sampler" / "vectorized.py").write_text(
        "state = self._dispatch(step, sub, params, state)\n"
        "state = step(sub, params, state)\n"
        "ok = finalize(state, params)  # retry-ok\n"
        "# a comment naming finalize(x) is not a violation\n"
        "jitted = jit_compile(step, donate_argnums=(2,))\n"
        "wire_dev, out_dev = finalize(state, params)\n")
    (pkg / "smc.py").write_text(
        "carry_out, wires = self._retry.call(fn, SITE, carry_in, key)\n"
        "carry_out, wires = fn(carry_in, key)\n")
    got = mod.check(root=str(pkg))
    assert [(path, lineno) for path, lineno, _ in got] == [
        ("sampler/vectorized.py", 2), ("sampler/vectorized.py", 6),
        ("smc.py", 2)]


def test_detects_unwrapped_chokepoint(tmp_path):
    """sampler/base.py dropping the SITE_FETCH retry routing is itself
    a violation — the d2h chokepoint rule."""
    mod = _load()
    pkg = tmp_path / "pkg"
    (pkg / "sampler").mkdir(parents=True)
    (pkg / "sampler" / "base.py").write_text(
        "def fetch_to_host(tree):\n"
        "    return jax.device_get(tree)\n")
    got = mod.check(root=str(pkg))
    assert {path for path, _, _ in got} == {"sampler/base.py"}
    assert len(got) == 2  # both markers missing


def test_cli_exit_codes(tmp_path, capsys):
    mod = _load()
    assert mod.main([]) == 0  # the real tree
    assert "clean" in capsys.readouterr().out
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "smc.py").write_text("carry_out, wires = fn(carry_in, key)\n")
    assert mod.main([str(pkg)]) == 1
    assert "smc.py:1" in capsys.readouterr().out
