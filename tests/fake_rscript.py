#!/usr/bin/env python3
"""Stub ``Rscript`` for wire-path tests (VERDICT r3 #6).

No R exists in this image, so the subprocess R transport
(pyabc_tpu/external/base.py `R._call`) never executed in CI.  This stub
is placed on PATH as ``Rscript`` and STRICTLY parses the exact
expression shape the transport generates::

    source("<file>"); .res <- fn(list(a=1.0), ...); .res <- as.list(.res);
    if (is.null(names(.res))) names(.res) <- paste0("v", seq_along(.res));
    cat(paste(names(.res), unlist(.res)), sep="\n", file="<target>")

Anything that deviates from that shape (a quoting regression, a changed
argument serialization, a missing source file) fails with a non-zero
exit, exercising the transport's error path too.  The function table
mirrors the R test fixture in tests/test_external.py.
"""
import os
import re
import sys


def fail(msg):
    print(f"fake_rscript: {msg}", file=sys.stderr)
    sys.exit(1)


def parse_r_list(text):
    """'list(a=1.0, b=2.0)' -> {'a': 1.0, 'b': 2.0} (floats only — the
    transport only ever serializes flat float dicts)."""
    m = re.fullmatch(r"list\((.*)\)", text.strip())
    if m is None:
        fail(f"malformed R list literal: {text!r}")
    inner = m.group(1).strip()
    out = {}
    if not inner:
        return out
    for item in inner.split(","):
        km = re.fullmatch(r"\s*([A-Za-z._][\w._]*)\s*=\s*([-+eE.\d]+)\s*",
                          item)
        if km is None:
            fail(f"malformed list item: {item!r}")
        out[km.group(1)] = float(km.group(2))
    return out


FUNCS = {
    "myModel": lambda pars: {"y": pars["mu"] * 2},
    "mySummary": lambda x: {"s": x["y"] + 1},
    "myDistance": lambda x, y: {"d": abs(x["s"] - y["s"])},
    "myObservation": lambda: {"s": 3.0},
    "myBroken": lambda *a: fail("myBroken always errors"),
}

EXPR_RE = re.compile(
    r'^source\("(?P<src>[^"]+)"\); '
    r"\.res <- (?P<fn>[A-Za-z._][\w._]*)(?:\((?P<args>.*)\))?; "
    r"\.res <- as\.list\(\.res\); "
    r"if \(is\.null\(names\(\.res\)\)\) "
    r'names\(\.res\) <- paste0\("v", seq_along\(\.res\)\); '
    r'cat\(paste\(names\(\.res\), unlist\(\.res\)\), sep="\\n", '
    r'file="(?P<target>[^"]+)"\)$')


def split_top_level(args):
    """Split 'list(a=1), list(b=2)' on top-level commas only."""
    parts, depth, cur = [], 0, ""
    for ch in args:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append(cur)
            cur = ""
        else:
            cur += ch
    if cur.strip():
        parts.append(cur)
    return parts


def main():
    if len(sys.argv) != 3 or sys.argv[1] != "-e":
        fail(f"expected ['-e', expr], got {sys.argv[1:]}")
    m = EXPR_RE.match(sys.argv[2])
    if m is None:
        fail(f"expression does not match the transport shape: "
             f"{sys.argv[2]!r}")
    if not os.path.exists(m.group("src")):
        fail(f"source file missing: {m.group('src')}")
    fn = FUNCS.get(m.group("fn"))
    if fn is None:
        fail(f"unknown function {m.group('fn')!r}")
    args = [parse_r_list(a) for a in
            split_top_level(m.group("args") or "")]
    res = fn(*args)
    with open(m.group("target"), "w") as f:
        f.write("\n".join(f"{k} {v}" for k, v in res.items()))


if __name__ == "__main__":
    main()
