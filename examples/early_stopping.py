"""Early stopping: fuse simulation with an early rejection decision.

The TPU edition of the reference's early-stopping notebook
(doc/examples, pyabc/model.py:273-328 ``IntegratedModel``): a model
that can already tell DURING simulation that a candidate will be
rejected — e.g. a trajectory that left the plausible region — reports
it through ``ModelResult.early_reject``.  In the reference this saves
the rest of a per-particle simulation; in the fused TPU round the mask
is OR-ed into rejection (sampler/rounds.py), so early-rejected lanes
never contaminate the accepted population and an ``IntegratedModel``
can skip expensive post-processing for doomed candidates.

Here: an SDE whose trajectories are monitored against a barrier — any
path that crosses it is rejected without computing summary statistics'
full distance machinery.

Run: ``python examples/early_stopping.py`` (ABC_EXAMPLE_POP shrinks it).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

import pyabc_tpu as pt
from pyabc_tpu.model import IntegratedModel, ModelResult

POP = int(os.environ.get("ABC_EXAMPLE_POP", 1000))
GENS = int(os.environ.get("ABC_EXAMPLE_GENS", 4))


class BarrierSDE(IntegratedModel):
    """dX = -theta·X dt + 0.2 dW from X0=1; paths crossing X > barrier
    are early-rejected (they already violate the known physics)."""

    def __init__(self, barrier: float = 1.6, n_steps: int = 50):
        super().__init__(name="barrier_sde")
        self.barrier = barrier
        self.n_steps = n_steps
        self.dt = 1.0 / n_steps

    def integrated_simulate(self, key, theta, eps):
        rate = jnp.exp(theta[:, 0])
        noise = jax.random.normal(key, (self.n_steps, theta.shape[0]))

        def step(carry, z):
            x, xmax = carry
            x = x - rate * x * self.dt + 0.2 * np.sqrt(self.dt) * z
            return (x, jnp.maximum(xmax, x)), None

        init = (jnp.ones(theta.shape[0]), jnp.ones(theta.shape[0]))
        (x_end, x_max), _ = lax.scan(step, init, noise)
        return ModelResult(sum_stats={"x_end": x_end},
                           early_reject=x_max > self.barrier)


def main():
    abc = pt.ABCSMC(
        models=BarrierSDE(),
        parameter_priors=pt.Distribution(log_rate=pt.RV("uniform",
                                                        -2.0, 3.0)),
        distance_function=pt.PNormDistance(p=2),
        population_size=POP,
        seed=2)
    abc.new("sqlite://", {"x_end": 0.37})  # ~exp(-1): rate ~ 1
    history = abc.run(max_nr_populations=GENS)

    df, w = history.get_distribution()
    est = float(np.exp(df["log_rate"].to_numpy()) @ w)
    print(f"posterior mean rate: {est:.3f} (signal ~1.0)")
    assert 0.3 < est < 3.0
    return history


if __name__ == "__main__":
    main()
