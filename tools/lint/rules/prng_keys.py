"""Rule ``prng-keys``: no PRNG key is consumed twice, and scan bodies
don't leak a consumed key back into the carry.

JAX PRNG keys are use-once values: passing the same key to two
``jax.random.*`` draws produces correlated (often identical) samples,
which in an ABC-SMC sampler silently collapses the effective particle
count — the posterior looks fine, the statistics are wrong.  The two
shapes this rule catches:

- **Double consumption** — a name bound to a key is passed to more
  than one consuming ``jax.random.*`` call without being rebound in
  between.  ``split`` COUNTS as a consumption of its argument (so
  ``sub = split(key)`` followed by ``normal(key)`` flags), and
  rebinding (``key, sub = jax.random.split(key)``) resets the name.
  ``fold_in`` does NOT consume — deriving many streams from one base
  key via distinct fold constants is the idiomatic fan-out (see
  ``sampler/fused.py``).  Uses in mutually exclusive ``if``/``else``
  branches don't conflict.
- **Scan-carry leak** — a ``lax.scan``/``while_loop`` body that
  consumes a key from its carry and then returns that SAME name in
  the new carry reuses the key on every iteration.  The fix is always
  ``key, sub = jax.random.split(key)`` and carrying the fresh half.

Keys are recognized by provenance (assigned from ``PRNGKey``/
``split``/``fold_in``/``wrap_key_data``), by the carry-unpack of a
scan body whose element names contain ``key``/``rng``, and by
parameter names containing ``key``/``rng``.

Suppress a deliberate reuse (e.g. common random numbers across
configs) with ``# graftlint: allow(prng-keys)``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..core import (Finding, Rule, ancestors, attach_parents, dotted_name,
                    register)

#: jax.random constructors whose RESULT is a key
_KEY_MAKERS = {"PRNGKey", "key", "split", "fold_in", "wrap_key_data",
               "clone"}

#: jax.random calls that do NOT consume their key argument
_NON_CONSUMING = {"fold_in", "key_data", "wrap_key_data", "clone",
                  "key_impl"}

_SCAN_CALLS = {"lax.scan", "jax.lax.scan",
               "lax.while_loop", "jax.lax.while_loop"}


def _random_fn(call: ast.Call) -> Optional[str]:
    """'split' for jax.random.split(...) / random.split(...) /
    jr.split(...); None for non-jax.random calls."""
    name = dotted_name(call.func)
    if not name or "." not in name:
        return None
    head, _, fn = name.rpartition(".")
    if head in ("jax.random", "random", "jrandom", "jr") \
            or head.endswith(".random"):
        return fn
    return None


def _branch_path(node: ast.AST) -> Tuple[Tuple[int, str], ...]:
    """(if-node-id, arm) pairs from outermost to ``node`` — two uses
    conflict only when neither diverges from the other at a shared
    ``if`` (i.e. one path is a prefix of the other)."""
    path: List[Tuple[int, str]] = []
    child = node
    for anc in ancestors(node):
        if isinstance(anc, ast.If):
            arm = "body" if any(child is n or child in ast.walk(n)
                                for n in anc.body) else "else"
            path.append((id(anc), arm))
        child = anc
    return tuple(reversed(path))


def _conflicting(a: Tuple, b: Tuple) -> bool:
    for (ia, arma), (ib, armb) in zip(a, b):
        if ia == ib and arma != armb:
            return False
    return True


def _name_targets(target: ast.AST) -> List[str]:
    return [n.id for n in ast.walk(target) if isinstance(n, ast.Name)]


def _looks_like_key(name: str) -> bool:
    low = name.lower()
    return "key" in low or "rng" in low


def _scan_body_names(tree: ast.Module) -> Set[str]:
    """Names of functions passed to lax.scan/while_loop in this
    module."""
    out: Set[str] = set()
    for call in (n for n in ast.walk(tree) if isinstance(n, ast.Call)):
        if dotted_name(call.func) in _SCAN_CALLS:
            for arg in call.args[:2]:
                if isinstance(arg, ast.Name):
                    out.add(arg.id)
    return out


class _FnState:
    """Per-function linear walk: key vars, per-name consumption count
    since last rebind, and recorded violations."""

    def __init__(self, rel: str, fn: ast.FunctionDef,
                 is_scan_body: bool):
        self.rel = rel
        self.fn = fn
        self.is_scan_body = is_scan_body
        self.keys: Set[str] = set()
        #: name -> list of (lineno, branch-path) consumptions
        self.uses: Dict[str, List[Tuple[int, Tuple]]] = {}
        self.violations: List[Tuple[str, int, str]] = []
        args = fn.args
        for a in (args.posonlyargs + args.args + args.kwonlyargs):
            if _looks_like_key(a.arg):
                self.keys.add(a.arg)

    def rebind(self, names: List[str], value: ast.AST):
        fn = _random_fn(value) if isinstance(value, ast.Call) else None
        for name in names:
            if fn in _KEY_MAKERS or _looks_like_key(name):
                self.keys.add(name)
            self.uses.pop(name, None)   # rebinding resets the counter

    def consume(self, name: str, lineno: int, where: ast.AST):
        if name not in self.keys:
            return
        path = _branch_path(where)
        prior = self.uses.setdefault(name, [])
        for plineno, ppath in prior:
            if _conflicting(ppath, path):
                self.violations.append((
                    self.rel, lineno,
                    f"key {name!r} consumed again in `{self.fn.name}` "
                    f"(first use line {plineno}; split before "
                    f"reusing)"))
                break
        prior.append((lineno, path))

    def returned_carry_names(self, node: ast.Return) -> List[str]:
        if not self.is_scan_body or node.value is None:
            return []
        val = node.value
        if isinstance(val, ast.Tuple) and val.elts:
            val = val.elts[0]       # (carry, y): carry is element 0
        return [n.id for n in ast.walk(val)
                if isinstance(n, ast.Name)]


def _terminates(body: List[ast.stmt]) -> bool:
    return bool(body) and isinstance(
        body[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break))


def _walk_fn(state: _FnState):
    """Statement-ordered walk of the function's own body."""
    def visit(node: ast.AST):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node is not state.fn:
            return
        if isinstance(node, ast.If):
            # an arm that exits the function cannot conflict with the
            # code after the ``if`` — roll its consumptions back
            visit(node.test)
            snap = {k: list(v) for k, v in state.uses.items()}
            for stmt in node.body:
                visit(stmt)
            if _terminates(node.body):
                state.uses = snap
            snap = {k: list(v) for k, v in state.uses.items()}
            for stmt in node.orelse:
                visit(stmt)
            if node.orelse and _terminates(node.orelse):
                state.uses = snap
            return
        if isinstance(node, ast.Assign):
            visit(node.value)
            for t in node.targets:
                state.rebind(_name_targets(t), node.value)
            return
        if isinstance(node, ast.Call):
            for child in ast.iter_child_nodes(node):
                visit(child)
            fn = _random_fn(node)
            if fn is not None and fn not in _NON_CONSUMING:
                candidates = list(node.args) \
                    + [kw.value for kw in node.keywords]
                for arg in candidates:
                    if isinstance(arg, ast.Name):
                        state.consume(arg.id, node.lineno, node)
            return
        if isinstance(node, ast.Return):
            for name in state.returned_carry_names(node):
                if name in state.keys and state.uses.get(name):
                    state.violations.append((
                        state.rel, node.lineno,
                        f"scan body `{state.fn.name}` consumes key "
                        f"{name!r} but returns it in the carry — the "
                        f"next iteration reuses it (split and carry "
                        f"the fresh key)"))
            for child in ast.iter_child_nodes(node):
                visit(child)
            return
        for child in ast.iter_child_nodes(node):
            visit(child)

    for stmt in state.fn.body:
        visit(stmt)


def check(files) -> List[Tuple[str, int, str]]:
    """``files`` is an iterable of (rel, ast.Module or None) pairs;
    returns ``[(rel, lineno, message), ...]``."""
    violations: List[Tuple[str, int, str]] = []
    for rel, tree in files:
        if tree is None:
            continue
        attach_parents(tree)
        scan_bodies = _scan_body_names(tree)
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            state = _FnState(rel, node, node.name in scan_bodies)
            _walk_fn(state)
            violations.extend(state.violations)
    violations.sort()
    return violations


@register
class PrngKeysRule(Rule):
    id = "prng-keys"
    description = ("PRNG keys are consumed once per binding; scan "
                   "carries never recycle a consumed key")

    def run(self, tree):
        prefix = tree.package_rel_prefix()
        pairs = [(sf.rel, sf.tree) for sf in tree.package_files()]
        return [Finding(self.id, f"{prefix}/{rel}", lineno, msg)
                for rel, lineno, msg in check(pairs)]
