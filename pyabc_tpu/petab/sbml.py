"""Minimal SBML subset parser + math-expression compiler (no libsbml).

The reference's PEtab pipeline compiles the SBML model through AMICI
(pyabc/petab/amici.py:26-170, model compile at :72-116); this image has
neither libsbml nor AMICI, and the TPU path needs a JAX-traceable batched
RHS anyway — so this module vendors the small subset of SBML that covers
reaction-network (mass-action/kinetic-law) and rate-rule models:

- ``listOfCompartments`` / ``listOfSpecies`` / ``listOfParameters``
- ``listOfReactions`` with MathML kinetic laws
- ``listOfRules``: rateRule + assignmentRule

Unsupported constructs (events, function definitions, initial assignments,
algebraic rules, delays, piecewise) raise a clear error instead of
silently mis-simulating.

Math handling: MathML is converted to plain infix strings; infix strings
(also used directly by PEtab observable/noise formulas) are parsed with
Python's ``ast`` module, validated against a whitelist, and evaluated
against an environment of JAX arrays — evaluation happens at trace time,
so the compiled XLA program contains only the resulting arithmetic.
"""

from __future__ import annotations

import ast
import math
import xml.etree.ElementTree as ET
from dataclasses import dataclass, field
from typing import Callable, Dict, List

import jax.numpy as jnp


# ---------------------------------------------------------------------------
# infix expression compiler
# ---------------------------------------------------------------------------

_ALLOWED_CALLS = {
    "exp": jnp.exp, "log": jnp.log, "ln": jnp.log, "log10": jnp.log10,
    "log2": jnp.log2, "sqrt": jnp.sqrt, "abs": jnp.abs, "sin": jnp.sin,
    "cos": jnp.cos, "tan": jnp.tan, "tanh": jnp.tanh, "sinh": jnp.sinh,
    "cosh": jnp.cosh, "arcsin": jnp.arcsin, "arccos": jnp.arccos,
    "arctan": jnp.arctan, "floor": jnp.floor, "ceil": jnp.ceil,
    "pow": jnp.power, "power": jnp.power,
    "min": jnp.minimum, "max": jnp.maximum,
}

_ALLOWED_NODES = (
    ast.Expression, ast.BinOp, ast.UnaryOp, ast.Call, ast.Name,
    ast.Constant, ast.Add, ast.Sub, ast.Mult, ast.Div, ast.Pow,
    ast.USub, ast.UAdd, ast.Load,
)


class ExprError(ValueError):
    """Unsupported or malformed model math."""


def parse_expr(formula: str) -> ast.Expression:
    """Parse an infix math string (PEtab/SBML style, ``^`` = power) into a
    validated Python AST."""
    source = str(formula).replace("^", "**")
    try:
        tree = ast.parse(source, mode="eval")
    except SyntaxError as err:
        raise ExprError(f"cannot parse formula {formula!r}: {err}") from None
    for node in ast.walk(tree):
        if not isinstance(node, _ALLOWED_NODES):
            raise ExprError(
                f"unsupported construct {type(node).__name__} in "
                f"formula {formula!r}")
        if isinstance(node, ast.Call):
            if (not isinstance(node.func, ast.Name)
                    or node.func.id not in _ALLOWED_CALLS):
                raise ExprError(f"unsupported function call in {formula!r}")
    return tree


def expr_names(formula: str) -> set:
    """Free symbols of a formula (function names excluded)."""
    tree = parse_expr(formula)
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            names.add(node.id)
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
            names.discard(node.func.id)
    # re-add names that are both called and referenced (impossible in the
    # subset, but keep the walk honest)
    return {n for n in names if n not in _ALLOWED_CALLS}


def eval_expr(formula: str, env: Dict[str, object]):
    """Evaluate a validated formula against ``env`` (names -> JAX arrays /
    scalars).  Runs at trace time; unknown names raise ExprError."""
    tree = parse_expr(formula)
    scope = dict(_ALLOWED_CALLS)
    scope.update({"pi": math.pi, "exponentiale": math.e, "e": math.e,
                  "true": 1.0, "false": 0.0, "avogadro": 6.02214076e23})
    scope.update(env)
    for name in expr_names(formula):
        if name not in scope:
            raise ExprError(f"unknown symbol {name!r} in formula "
                            f"{formula!r} (available: model entities)")
    code = compile(tree, "<sbml-math>", "eval")
    return eval(code, {"__builtins__": {}}, scope)


# ---------------------------------------------------------------------------
# MathML -> infix
# ---------------------------------------------------------------------------

_MATHML_OPS = {
    "plus": " + ", "minus": " - ", "times": " * ", "divide": " / ",
    "power": " ** ",
}
_MATHML_FUNCS = {
    "exp", "ln", "log", "root", "abs", "sin", "cos", "tan", "tanh",
    "sinh", "cosh", "arcsin", "arccos", "arctan", "floor", "ceiling",
}


def _local(tag: str) -> str:
    return tag.rsplit("}", 1)[-1]


def mathml_to_infix(node: ET.Element) -> str:
    """Convert a MathML ``<math>``/operand element to an infix string."""
    tag = _local(node.tag)
    if tag == "math":
        children = list(node)
        if len(children) != 1:
            raise ExprError("expected a single MathML root expression")
        return mathml_to_infix(children[0])
    if tag == "ci":
        return node.text.strip()
    if tag == "cn":
        cn_type = node.get("type", "real")
        if cn_type in ("e-notation", "rational"):
            parts = [t.strip() for t in node.itertext() if t.strip()]
            if len(parts) != 2:
                raise ExprError(f"malformed <cn type={cn_type!r}>")
            a, b = float(parts[0]), float(parts[1])
            val = a * 10.0**b if cn_type == "e-notation" else a / b
            return repr(val)
        return repr(float(node.text.strip()))
    if tag == "csymbol":
        # definitionURL .../symbols/time (or avogadro)
        url = node.get("definitionURL", "")
        if url.endswith("time"):
            return "time"
        if url.endswith("avogadro"):
            return "avogadro"
        raise ExprError(f"unsupported csymbol {url!r}")
    if tag == "apply":
        children = list(node)
        op = _local(children[0].tag)
        # qualifier elements (<logbase>, <degree>) are handled by their
        # operator below, not converted as operands
        operands = [c for c in children[1:]
                    if _local(c.tag) not in ("logbase", "degree")]
        args = [mathml_to_infix(c) for c in operands]
        if op in _MATHML_OPS:
            if op == "minus" and len(args) == 1:
                return f"(-{args[0]})"
            if not args:
                raise ExprError(f"<{op}/> with no operands")
            return "(" + _MATHML_OPS[op].join(args) + ")"
        if op in _MATHML_FUNCS:
            fn = {"ceiling": "ceil", "ln": "log"}.get(op, op)
            if op == "log":
                # MathML log may carry a <logbase>
                base_elems = [c for c in children[1:]
                              if _local(c.tag) == "logbase"]
                if base_elems:
                    base = mathml_to_infix(list(base_elems[0])[0])
                    operand = args[-1]
                    return f"(log({operand}) / log({base}))"
                fn = "log10"  # MathML <log/> without base is log10
            if op == "root":
                degree_elems = [c for c in children[1:]
                                if _local(c.tag) == "degree"]
                if degree_elems:
                    deg = mathml_to_infix(list(degree_elems[0])[0])
                    return f"(({args[-1]}) ** (1.0 / ({deg})))"
                return f"sqrt({args[-1]})"
            return f"{fn}({', '.join(args)})"
        raise ExprError(f"unsupported MathML operator <{op}>")
    if tag == "piecewise":
        raise ExprError("SBML piecewise is not supported by the vendored "
                        "subset parser")
    raise ExprError(f"unsupported MathML element <{tag}>")


# ---------------------------------------------------------------------------
# SBML document model
# ---------------------------------------------------------------------------

@dataclass
class SBMLSpecies:
    id: str
    compartment: str
    initial: float
    boundary: bool = False
    constant: bool = False


@dataclass
class SBMLReaction:
    id: str
    reactants: List  # (species id, stoichiometry)
    products: List
    kinetic_law: str  # infix formula


@dataclass
class SBMLModel:
    """Parsed SBML subset: everything needed to build a batched RHS."""
    species: Dict[str, SBMLSpecies] = field(default_factory=dict)
    parameters: Dict[str, float] = field(default_factory=dict)
    compartments: Dict[str, float] = field(default_factory=dict)
    reactions: List[SBMLReaction] = field(default_factory=list)
    rate_rules: Dict[str, str] = field(default_factory=dict)
    assignment_rules: Dict[str, str] = field(default_factory=dict)

    # ---- derived structure ------------------------------------------------

    def state_ids(self) -> List[str]:
        """Dynamic state order: non-boundary non-constant species not
        governed by an assignment rule, then rate-rule-only targets
        (parameters under a rate rule)."""
        out = []
        for sid, sp in self.species.items():
            if sp.constant or sid in self.assignment_rules:
                continue
            out.append(sid)
        for target in self.rate_rules:
            if target not in out and target not in self.species:
                out.append(target)
        return out

    def y0(self) -> List[float]:
        vals = []
        for sid in self.state_ids():
            if sid in self.species:
                vals.append(self.species[sid].initial)
            else:
                vals.append(self.parameters[sid])
        return vals

    def base_env(self) -> Dict[str, float]:
        """Constant symbols: compartment sizes + (non-state) parameters +
        constant species."""
        env = dict(self.compartments)
        state = set(self.state_ids())
        for pid, val in self.parameters.items():
            if pid not in state:
                env[pid] = val
        for sid, sp in self.species.items():
            if sp.constant:
                env[sid] = sp.initial
        return env

    def resolve_assignments(self, env: Dict[str, object]
                            ) -> Dict[str, object]:
        """Evaluate assignment rules (topologically, bounded depth) into
        ``env``; returns the extended env."""
        env = dict(env)
        pending = dict(self.assignment_rules)
        for _ in range(len(pending) + 1):
            if not pending:
                break
            progressed = False
            for target, formula in list(pending.items()):
                if expr_names(formula) <= set(env) | set(_ALLOWED_CALLS):
                    env[target] = eval_expr(formula, env)
                    del pending[target]
                    progressed = True
            if not progressed:
                raise ExprError(
                    f"cyclic or unresolvable assignment rules: "
                    f"{sorted(pending)}")
        return env

    def make_rhs(self) -> Callable:
        """Batched JAX RHS ``rhs(y[N, S], theta_env) -> [N, S]``.

        ``theta_env`` maps ESTIMATED parameter ids to [N]-shaped arrays
        (unscaled); everything else resolves from the document.  Returned
        as ``rhs(y, theta_env, t=0.0)`` — time enters through rate laws
        that reference the csymbol ``time``.
        """
        state = self.state_ids()
        index = {sid: i for i, sid in enumerate(state)}
        base = self.base_env()

        def rhs(y, theta_env, t=0.0):
            env = dict(base)
            env.update(theta_env)
            env["time"] = t
            for sid, i in index.items():
                env[sid] = y[:, i]
            # boundary species: state participates in rate laws but is
            # held by rules/constants if also assigned
            env = self.resolve_assignments(env)
            def comp_size(sid):
                # the compartment size must come from env, not the static
                # document: condition-table overrides (or estimation) of
                # a size would otherwise change kinetic-law symbols but
                # not this stoichiometric division
                return env.get(self.species[sid].compartment, 1.0)

            dydt = [jnp.zeros(y.shape[0]) for _ in state]
            for rxn in self.reactions:
                rate = eval_expr(rxn.kinetic_law, env)
                rate = jnp.broadcast_to(rate, (y.shape[0],))
                for sid, stoich in rxn.reactants:
                    if sid in index and not self.species[sid].boundary:
                        dydt[index[sid]] = (dydt[index[sid]]
                                            - stoich * rate / comp_size(sid))
                for sid, stoich in rxn.products:
                    if sid in index and not self.species[sid].boundary:
                        dydt[index[sid]] = (dydt[index[sid]]
                                            + stoich * rate / comp_size(sid))
            for target, formula in self.rate_rules.items():
                val = eval_expr(formula, env)
                dydt[index[target]] = jnp.broadcast_to(val, (y.shape[0],))
            return jnp.stack(dydt, axis=-1)

        return rhs


_UNSUPPORTED_LISTS = {
    "listOfEvents": "events",
    "listOfFunctionDefinitions": "function definitions",
    "listOfInitialAssignments": "initial assignments",
    "listOfConstraints": "constraints",
}


def parse_sbml(path_or_string: str) -> SBMLModel:
    """Parse an SBML file (or XML string) into the subset model."""
    text = path_or_string
    if not path_or_string.lstrip().startswith("<"):
        with open(path_or_string) as f:
            text = f.read()
    root = ET.fromstring(text)
    model_elems = [c for c in root if _local(c.tag) == "model"]
    if not model_elems:
        raise ExprError("no <model> element in SBML document")
    melem = model_elems[0]

    doc = SBMLModel()
    amount_species: List[str] = []
    for section in melem:
        tag = _local(section.tag)
        if tag in _UNSUPPORTED_LISTS:
            raise ExprError(
                f"SBML {_UNSUPPORTED_LISTS[tag]} are not supported by the "
                "vendored subset parser")
        if tag == "listOfCompartments":
            for c in section:
                doc.compartments[c.get("id")] = float(c.get("size", 1.0))
        elif tag == "listOfSpecies":
            for s in section:
                init = s.get("initialConcentration")
                if init is None:
                    init = s.get("initialAmount")
                    # a NONZERO amount only coincides with concentration
                    # in a unit compartment; anything else would silently
                    # mis-simulate (the /size division assumes
                    # concentrations) — checked after all sections parse.
                    # Zero amounts (empty product species) and absent
                    # initials (set via condition tables) are fine.
                    if init is not None and float(init) != 0.0:
                        amount_species.append(s.get("id"))
                    init = init if init is not None else "0"
                if s.get("hasOnlySubstanceUnits") == "true":
                    raise ExprError(
                        f"species {s.get('id')!r} uses "
                        "hasOnlySubstanceUnits, which the vendored subset "
                        "parser does not support (concentration semantics "
                        "only)")
                doc.species[s.get("id")] = SBMLSpecies(
                    id=s.get("id"),
                    compartment=s.get("compartment", ""),
                    initial=float(init),
                    boundary=s.get("boundaryCondition") == "true",
                    constant=s.get("constant") == "true")
        elif tag == "listOfParameters":
            for p in section:
                doc.parameters[p.get("id")] = float(p.get("value", 0.0))
        elif tag == "listOfRules":
            for r in section:
                rtag = _local(r.tag)
                math_elems = [c for c in r if _local(c.tag) == "math"]
                if not math_elems:
                    raise ExprError(f"rule without <math> for "
                                    f"{r.get('variable')!r}")
                formula = mathml_to_infix(math_elems[0])
                if rtag == "rateRule":
                    doc.rate_rules[r.get("variable")] = formula
                elif rtag == "assignmentRule":
                    doc.assignment_rules[r.get("variable")] = formula
                else:
                    raise ExprError(f"unsupported rule type <{rtag}>")
        elif tag == "listOfReactions":
            for r in section:
                reactants, products, law = [], [], None
                for part in r:
                    ptag = _local(part.tag)
                    if ptag in ("listOfReactants", "listOfProducts"):
                        dest = (reactants if ptag == "listOfReactants"
                                else products)
                        for ref in part:
                            dest.append((ref.get("species"),
                                         float(ref.get("stoichiometry",
                                                       1.0))))
                    elif ptag == "kineticLaw":
                        math_elems = [c for c in part
                                      if _local(c.tag) == "math"]
                        if not math_elems:
                            raise ExprError(
                                f"reaction {r.get('id')!r} kineticLaw "
                                "without <math>")
                        # local kineticLaw parameters: SBML scopes them
                        # per-reaction, but this subset flattens them into
                        # the global table — an id collision would
                        # silently rebind other formulas, so it raises
                        local_env = {}
                        for sub in part:
                            if _local(sub.tag) in ("listOfParameters",
                                                   "listOfLocalParameters"):
                                for p in sub:
                                    local_env[p.get("id")] = float(
                                        p.get("value", 0.0))
                        law = mathml_to_infix(math_elems[0])
                        for pid in local_env:
                            if pid in doc.parameters or pid in doc.species \
                                    or pid in doc.compartments:
                                raise ExprError(
                                    f"local kineticLaw parameter {pid!r} "
                                    f"in reaction {r.get('id')!r} collides "
                                    "with a global id (per-reaction "
                                    "scoping is not supported)")
                        doc.parameters.update(local_env)
                if law is None:
                    raise ExprError(
                        f"reaction {r.get('id')!r} has no kinetic law")
                doc.reactions.append(SBMLReaction(
                    id=r.get("id"), reactants=reactants,
                    products=products, kinetic_law=law))
    for sid in amount_species:
        size = doc.compartments.get(doc.species[sid].compartment, 1.0)
        if size != 1.0:
            raise ExprError(
                f"species {sid!r} declares initialAmount in a "
                f"compartment of size {size} — amount/concentration "
                "conversion is not supported by the vendored subset "
                "parser (use initialConcentration or a unit compartment)")
    return doc
