"""Tier-1 gate for continuous batching on the study axis.

Pins the contracts docs/serving.md's "Continuous batching" section
advertises:

- lane-turnover bit identity: a study admitted into a freed lane
  mid-batch returns EXACTLY the bytes of the same study seated in a
  fresh batch of the same shape — admission point is invisible in the
  result;
- zero XLA recompiles across consecutive lane turnovers at a fixed
  batch shape (the program is re-entered, never re-traced);
- batch-shape hysteresis: refill is preferred over shrink, and a
  shrink transplants in-flight carries losslessly;
- drain (SIGTERM) at a window boundary keeps retired lanes' publishes
  and requeues unfinished lanes whole;
- keyed claims: the CB refill's ``claim(batch_key=...)`` filters
  without starving other keys and keeps aged-priority order within a
  key.
"""

import json
import os
import sys

import numpy as np
import pytest

_REPO = os.path.abspath(os.path.join(os.path.dirname(__file__),
                                     os.pardir))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

import pyabc_tpu as pt  # noqa: E402
from pyabc_tpu.autotune import (compile_counters,  # noqa: E402
                                install_compile_listener)
from pyabc_tpu.serve import (ServeWorker, ShapeHysteresis,  # noqa: E402
                             StudyBatch, StudyQueue, StudySpec)
from pyabc_tpu.serve.multiplex import batch_key  # noqa: E402


def _model(key, theta):
    """Quickstart-shaped simulator; module-level because queue
    submissions pickle the spec, exactly like a real tenant's
    importable model."""
    import jax
    noise = 0.1 * jax.random.normal(key, (theta.shape[0], 1))
    return {"y": theta[:, :1] + noise}


def _spec(pop=100, seed=0, tenant="default", y=0.4, **kw):
    return StudySpec(
        model=_model,
        prior=pt.Distribution(mu=pt.RV("uniform", -1.0, 2.0)),
        observed={"y": float(y)}, population_size=pop,
        seed=seed, tenant=tenant,
        max_generations=kw.pop("max_generations", 3), **kw)


def _drain(batch):
    """Step windows until every occupied lane stopped; returns
    {slot: result} snapshots taken at each lane's own boundary."""
    out = {}
    for _ in range(64):
        for slot in batch.step_window():
            out[slot] = batch.result(slot)
            batch.retire(slot)
        if not batch.unfinished():
            break
    assert not batch.unfinished(), "batch never drained"
    return out


def _assert_same_bits(got, want, context=""):
    assert set(got) == set(want)
    for k in sorted(got):
        a, b = np.asarray(got[k]), np.asarray(want[k])
        assert np.array_equal(a, b), f"{context}{k}"


# ---------------------------------------------------------------------------
# lane turnover: bit identity + zero recompiles
# ---------------------------------------------------------------------------

def test_lane_turnover_bit_identity():
    """THE continuous-batching gate: a study admitted into a lane
    freed mid-batch (after its predecessor retired at a window
    boundary) is BITWISE equal — every key, including the distance
    diagnostic — to the same study seated at window 0 of a fresh
    batch of the same shape, because both run the SAME compiled
    program with the admission masked in per-lane."""
    programs = {}
    long0 = _spec(pop=100, seed=0, y=0.2, max_generations=3)
    short = _spec(pop=100, seed=1, y=-0.1, max_generations=2)
    late = _spec(pop=100, seed=2, y=0.5, max_generations=3)

    batch = StudyBatch([long0, short], program_cache=programs, window=1)
    results = {}
    admitted_late = False
    for _ in range(64):
        for slot in batch.step_window():
            spec = batch.slots[slot]
            results[spec.seed] = batch.result(slot)
            batch.retire(slot)
            if not admitted_late:  # the turnover under test
                assert batch.admit(late) == slot
                admitted_late = True
        if admitted_late and not batch.unfinished():
            break
    assert admitted_late and batch.turnovers >= 2
    assert set(results) == {0, 1, 2}

    # reference: each study at window 0 of a fresh same-shape batch,
    # SAME program cache — the compiled fn is shared, so equality is
    # byte-for-byte on every key (no cross-rung dist carve-out needed)
    for spec in (long0, short, late):
        dummy = _spec(pop=100, seed=90 + spec.seed, y=0.0)
        ref = StudyBatch([spec, dummy], program_cache=programs,
                         window=1)
        assert ref.program_cache_hit
        _assert_same_bits(results[spec.seed], _drain(ref)[0],
                          context=f"seed {spec.seed}: ")


def test_zero_recompiles_across_lane_turnovers():
    """Three consecutive admit/retire turnovers at a fixed batch shape
    re-enter the pooled program: XLA compile delta is ZERO after the
    first window (the ISSUE's headline counter-assertion)."""
    install_compile_listener()
    programs = {}
    batch = StudyBatch(
        [_spec(pop=100, seed=0, max_generations=2),
         _spec(pop=100, seed=1, max_generations=2)],
        program_cache=programs, window=1)
    batch.step_window()  # first dispatch pays the one compile
    n0 = compile_counters()["n_compiles"]
    waiting = [_spec(pop=100, seed=s, max_generations=2)
               for s in (10, 11, 12)]
    for _ in range(64):
        for slot in batch.step_window():
            batch.retire(slot)
            if waiting:
                batch.admit(waiting.pop(0), slot=slot)
        if not waiting and not batch.unfinished():
            break
    assert not batch.unfinished()
    assert batch.turnovers >= 3 and batch.admitted == 5
    assert compile_counters()["n_compiles"] == n0, (
        "lane turnover re-traced the batch program")


# ---------------------------------------------------------------------------
# hysteresis + shrink
# ---------------------------------------------------------------------------

def test_shape_hysteresis_prefers_refill_over_shrink():
    h = ShapeHysteresis(shrink_after=3)
    # two underfilled windows: not enough evidence yet
    assert not h.observe(1, 4)
    assert not h.observe(1, 4)
    # a refill lands: streak resets (refill beat shrink)
    assert not h.observe(3, 4)
    # sustained underfill: the THIRD consecutive window triggers
    assert not h.observe(1, 4)
    assert not h.observe(1, 4)
    assert h.observe(1, 4)
    # ...and the trigger consumed the streak
    assert not h.observe(1, 4)
    # rung 1 can never shrink; an empty batch never shrinks mid-drain
    for _ in range(5):
        assert not h.observe(1, 1)
        assert not h.observe(0, 4)


def test_shrink_transplants_inflight_lanes():
    """A shrink mid-run moves every occupied lane's carry onto the
    narrower rung losslessly: the survivor finishes with the same
    populations as an all-solo run (dist gets the documented 1-ULP
    cross-rung carve-out), and the turnover counters carry over."""
    programs = {}
    survivor = _spec(pop=100, seed=0, y=0.2, max_generations=4)
    batch = StudyBatch(
        [survivor, _spec(pop=100, seed=1, max_generations=2),
         _spec(pop=100, seed=2, max_generations=2)],
        program_cache=programs, window=1)
    assert batch.rung == 4
    finished = batch.step_window()
    for slot in finished:
        batch.retire(slot)
    assert batch.occupied() == 1 and batch.occupancy() == 0.25
    small, slot_map = batch.shrink(program_cache=programs)
    assert small.rung == 1 and slot_map == {0: 0}
    assert small.turnovers == batch.turnovers
    assert small.admitted == batch.admitted
    got = _drain(small)[0]
    want = _drain(StudyBatch([survivor], program_cache=programs,
                             window=1))[0]
    assert set(got) == set(want)
    for k in sorted(got):
        a, b = np.asarray(got[k]), np.asarray(want[k])
        if k == "dist":
            assert np.all(np.abs(a - b)
                          <= np.spacing(np.float32(0.5))), k
        else:
            assert np.array_equal(a, b), k


# ---------------------------------------------------------------------------
# the windowed queue loop: early publish, drain, refill
# ---------------------------------------------------------------------------

def test_drain_mid_session_keeps_publishes_requeues_rest(
        tmp_path, monkeypatch):
    """SIGTERM between windows: the lane that retired before the drain
    keeps its tombstone (early publish is durable), every unfinished
    lane is requeued whole with its bounce counted."""
    monkeypatch.setenv("PYABC_TPU_SERVE_MULTIPLEX", "4")
    monkeypatch.setenv("PYABC_TPU_SERVE_CB_WINDOW", "1")
    queue = StudyQueue(root=str(tmp_path))
    t_short = queue.submit(_spec(seed=0, max_generations=2))
    t_long = queue.submit(_spec(seed=1, max_generations=6))
    worker = ServeWorker(root=str(tmp_path))
    publish = worker._cb_publish_lane

    def publish_then_drain(*args, **kw):
        publish(*args, **kw)  # the SIGTERM lands after this publish
        worker.drain()
    monkeypatch.setattr(worker, "_cb_publish_lane", publish_then_drain)
    served = worker.run_forever(queue, once=True)
    assert served == 1
    stats = queue.stats()
    assert (stats["pending"], stats["claimed"], stats["done"],
            stats["failed"]) == (1, 0, 1, 0)
    tomb = json.load(open(os.path.join(
        queue.root, "done", f"{t_short.id}.json"), encoding="utf-8"))
    assert tomb["engine"] == "multiplex"
    (back,) = queue.pending()
    assert back.id == t_long.id and back.requeues == 1


def test_refill_claims_same_key_work_mid_session(tmp_path, monkeypatch):
    """Four same-``batch_key`` studies against a width-2 worker drain
    in ONE windowed session: the two claimed up front seed the batch,
    the other two join through the keyed refill claim as lanes retire.
    Every lane's trace carries its join/retire markers."""
    monkeypatch.setenv("PYABC_TPU_SERVE_MULTIPLEX", "2")
    monkeypatch.setenv("PYABC_TPU_SERVE_CB_WINDOW", "1")
    monkeypatch.setenv("PYABC_TPU_SERVE_TRACE", "1")
    queue = StudyQueue(root=str(tmp_path))
    tickets = [queue.submit(_spec(seed=s, y=0.1 * s, max_generations=2))
               for s in range(4)]
    worker = ServeWorker(root=str(tmp_path))
    served = worker.run_forever(queue, once=True)
    assert served == 4
    stats = queue.stats()
    assert (stats["pending"], stats["claimed"], stats["done"],
            stats["failed"]) == (0, 0, 4, 0)
    from pyabc_tpu.telemetry.studytrace import StudyTrace
    for t in tickets:
        trace = StudyTrace.assemble(str(tmp_path), t.id)
        names = trace.event_names()
        assert names.count("lane_joined") == 1, names
        assert names.count("lane_retired") == 1, names
        assert names.index("lane_joined") < names.index("published")


# ---------------------------------------------------------------------------
# keyed claims
# ---------------------------------------------------------------------------

def test_keyed_claim_filters_and_keeps_aged_priority(tmp_path):
    q = StudyQueue(root=str(tmp_path), aging_s=1e9, partitions=1)
    spec_a_low = _spec(pop=100, seed=0, priority=0)
    spec_a_high = _spec(pop=100, seed=1, priority=5)
    spec_b = _spec(pop=200, seed=2)  # pop is program shape: new key
    key_a, key_b = batch_key(spec_a_low), batch_key(spec_b)
    assert key_a != key_b
    t_low = q.submit(spec_a_low)
    t_high = q.submit(spec_a_high)
    t_b = q.submit(spec_b)
    # unknown key starves rather than mis-claims
    assert q.claim("w1", batch_key="f" * 64) is None
    # within a key, aged-priority order is preserved
    assert q.claim("w1", batch_key=key_a).id == t_high.id
    assert q.claim("w1", batch_key=key_a).id == t_low.id
    assert q.claim("w1", batch_key=key_a) is None
    # the other key's work was never touched
    assert q.claim("w1", batch_key=key_b).id == t_b.id


def test_keyed_claim_skips_prestamp_tickets(tmp_path):
    """A pending file submitted before the batch_key stamp existed
    (no ``batch_key`` field) is invisible to keyed claims — never
    mis-grouped — but still served by the plain claim path."""
    q = StudyQueue(root=str(tmp_path), partitions=1)
    spec = _spec(pop=100, seed=0)
    t = q.submit(spec)
    with open(t.path, encoding="utf-8") as f:
        payload = json.load(f)
    del payload["batch_key"]
    with open(t.path, "w", encoding="utf-8") as f:
        json.dump(payload, f)
    assert q.claim("w1", batch_key=batch_key(spec)) is None
    plain = q.claim("w1")
    assert plain is not None and plain.id == t.id
