"""Rule ``env-drift``: the ``PYABC_TPU_*`` environment surface in code
and in docs is the SAME set.

Every operational knob in this repo is a ``PYABC_TPU_*`` environment
variable, and ``docs/`` is the contract for operators driving fleet
runs.  Drift is deadly in both directions: an undocumented variable is
a knob nobody can discover (it gets re-invented under a second name),
and a documented-but-removed variable is an operator setting it in a
launch script and silently getting the default.

Check: collect every ``PYABC_TPU_[A-Z0-9_]+`` token from
``pyabc_tpu/**/*.py`` and from ``docs/*.md``; the two sets must be
equal.  The allowlist below is deliberately EMPTY at seed — add a
variable only with a justification comment (e.g. a var that exists
solely for a test harness and must not be in operator docs).

Findings are anchored to the first occurrence (code side) or the docs
file (docs side).  Inline ``# graftlint: allow(env-drift)`` on the
defining line also works for code-side findings.
"""

from __future__ import annotations

import re
from typing import List, Tuple

from ..core import Finding, Rule, register

_VAR = re.compile(r"\bPYABC_TPU_[A-Z0-9_]+\b")

#: vars exempt from the two-way check.  EMPTY on purpose — grow it
#: only with a justification comment per entry.
ALLOWLIST: frozenset = frozenset()


def check(package_files, docs_files) -> List[Tuple[str, int, str]]:
    """Both arguments are iterables of objects with ``.rel``,
    ``.lines``; returns ``[(rel, lineno, message), ...]`` where rel is
    the argument object's own rel path."""
    code_first: dict = {}   # var -> (rel, lineno)
    for sf in package_files:
        for lineno, line in enumerate(sf.lines, 1):
            for var in _VAR.findall(line):
                code_first.setdefault(var, (sf.rel, lineno))
    docs_first: dict = {}
    for sf in docs_files:
        for lineno, line in enumerate(sf.lines, 1):
            for var in _VAR.findall(line):
                docs_first.setdefault(var, (sf.rel, lineno))
    violations: List[Tuple[str, int, str]] = []
    for var in sorted(set(code_first) - set(docs_first) - ALLOWLIST):
        rel, lineno = code_first[var]
        violations.append((
            rel, lineno,
            f"{var} is read in code but documented nowhere under "
            f"docs/ — add it to the relevant ops doc"))
    for var in sorted(set(docs_first) - set(code_first) - ALLOWLIST):
        rel, lineno = docs_first[var]
        violations.append((
            rel, lineno,
            f"{var} is documented but no longer read by any code — "
            f"drop it from the docs or restore the knob"))
    violations.sort()
    return violations


@register
class EnvDriftRule(Rule):
    id = "env-drift"
    description = ("every PYABC_TPU_* env var is documented, and every "
                   "documented one still exists in code")

    def run(self, tree):
        prefix = tree.package_rel_prefix()
        pkg = tree.package_files()
        docs = tree.repo_glob("docs", ".md")
        out = []
        for rel, lineno, msg in check(pkg, docs):
            # package files carry package-relative rels; docs carry
            # repo-relative rels already
            path = rel if rel.startswith("docs/") \
                else f"{prefix}/{rel}"
            out.append(Finding(self.id, path, lineno, msg))
        return out
