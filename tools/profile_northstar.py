"""Profile the north-star (pop 1e6) generation: component shares.

Run on the real TPU:  python tools/profile_northstar.py
"""
import json
import time

import numpy as np

import jax
jax.config.update("jax_compilation_cache_dir", "/tmp/pyabc_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
import jax.numpy as jnp


def _sync(out):
    """block_until_ready doesn't actually block through the axon relay;
    force completion with a scalar reduce + host fetch (~0.2 s constant)."""
    leaves = jax.tree_util.tree_leaves(out)
    return float(sum(jnp.sum(jnp.asarray(l, jnp.float32).ravel()[:1])
                     for l in leaves))


def timed(fn, *args, n=3, **kw):
    _sync(fn(*args, **kw))
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        _sync(fn(*args, **kw))
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts)), None


def main():
    res = {}
    B = 1 << 19
    N = 1 << 20
    d = 1
    key = jax.random.PRNGKey(0)

    # --- KDE logpdf at north-star shape, XLA vs Pallas -------------------
    from pyabc_tpu.ops.kde import weighted_kde_logpdf
    from pyabc_tpu.ops.kde_pallas import (pallas_available,
                                          weighted_kde_logpdf_pallas)
    support = jax.random.normal(key, (N, d), dtype=jnp.float32)
    log_w = jnp.full((N,), -float(np.log(N)), jnp.float32)
    chol = jnp.eye(d, dtype=jnp.float32) * 0.1
    log_norm = jnp.asarray(-d / 2 * np.log(2 * np.pi) - d * np.log(0.1),
                           jnp.float32)
    x = jax.random.normal(jax.random.fold_in(key, 1), (B, d), jnp.float32)
    t, _ = timed(weighted_kde_logpdf, x, support, log_w, chol, log_norm)
    res["kde_xla_B19_N20_s"] = round(t, 3)
    res["kde_xla_B19_N20_gpairs"] = round(B * N / t / 1e9, 1)
    if pallas_available():
        t, _ = timed(weighted_kde_logpdf_pallas, x, support, log_w, chol,
                     log_norm)
        res["kde_pallas_B19_N20_s"] = round(t, 3)
        res["kde_pallas_B19_N20_gpairs"] = round(B * N / t / 1e9, 1)
    # half-support (what per-model pow2 bucketing would give at p~0.5)
    for NB, tag in ((1 << 19, "N19"), (1 << 18, "N18")):
        t, _ = timed(weighted_kde_logpdf, x, support[:NB], log_w[:NB], chol,
                     log_norm)
        res[f"kde_xla_B19_{tag}_s"] = round(t, 3)

    # --- weighted choice at round shape ----------------------------------
    from pyabc_tpu.ops import fast_weighted_choice
    t, _ = timed(fast_weighted_choice, key, log_w, B)
    res["choice_B19_N20_s"] = round(t, 4)

    # --- device->host transfer of the finalize payload --------------------
    # device-COMPUTED arrays (host-created zeros may be served from a
    # client-side cache without a real transfer)
    n_target = 1_000_000

    def fresh_payload(i):
        # a FRESH device-computed payload each iteration: the relay
        # client caches arrays it has already fetched, so re-fetching
        # the same buffers reads ~0 s
        kk = jax.random.split(jax.random.fold_in(key, i), 6)
        # mirrors device_loop.narrow_wire's round-5 format (bit-packed
        # m, max-scaled f16 float columns)
        return {
            "m_bits": jnp.packbits(jax.random.randint(
                kk[0], (n_target,), 0, 2).astype(jnp.uint8)),
            "theta": jax.random.normal(kk[1], (n_target, 1),
                                       jnp.float16),
            "theta_scale": jnp.ones((1,), jnp.float32),
            "distance": jax.random.normal(kk[2], (n_target,),
                                          jnp.float16),
            "distance_scale": jnp.float32(1.0),
            "log_weight": jax.random.normal(kk[3], (n_target,),
                                            jnp.float16),
            "stats": jax.random.normal(kk[4], (n_target, 1),
                                       jnp.float16),
            "stats_scale": jnp.ones((1,), jnp.float32),
            "count": jnp.int32(0),
            "rounds": jnp.int32(0),
        }

    ts = []
    for i in range(3):
        payload = fresh_payload(i)
        _sync(payload)
        t0 = time.perf_counter()
        jax.device_get(payload)
        ts.append(time.perf_counter() - t0)
    res["finalize_fetch_s"] = round(float(np.median(ts)), 3)

    # --- full abc generation, instrumented --------------------------------
    import pyabc_tpu as pt
    from pyabc_tpu.models import make_two_gaussians_problem
    from pyabc_tpu.sampler import base as sampler_base

    models, priors, distance, observed, _ = make_two_gaussians_problem()
    abc = pt.ABCSMC(
        models, priors, distance,
        population_size=n_target,
        eps=pt.ConstantEpsilon(0.2),
        sampler=pt.VectorizedSampler(max_batch_size=1 << 19,
                                     max_rounds_per_call=2),
        seed=0)
    abc.new("sqlite://", observed)

    res["ingest_overlap_enabled"] = abc._overlap_enabled()

    # NOTE: at this population ingest_mode="auto" routes the overlapped
    # wire pipeline (pyabc_tpu/wire/), where accepted populations travel
    # as pending wires through StreamingIngest tickets and
    # Sample.append_device_batch never runs — the marks below then stay
    # empty and the per-stage split comes from generation_transfer
    # (compute_s / fetch_s / overlap_s) dumped at the end instead.
    marks = []
    orig_adb = sampler_base.Sample.append_device_batch

    def patched_adb(self, out, n_evals, *args, **kwargs):
        t0 = time.perf_counter()
        r = orig_adb(self, out, n_evals, *args, **kwargs)
        marks.append(("append_device_batch", time.perf_counter() - t0))
        return r

    sampler_base.Sample.append_device_batch = patched_adb

    t0 = time.perf_counter()
    abc.run(max_nr_populations=2)   # warmup: calibration + prior + 1 kde gen
    res["warmup_2gen_s"] = round(time.perf_counter() - t0, 2)
    marks.clear()
    t0 = time.perf_counter()
    abc.run(max_nr_populations=1)
    res["gen_total_s"] = round(time.perf_counter() - t0, 2)
    res["marks"] = [(k, round(v, 3)) for k, v in marks]

    # separately: sampling-only time for one more generation (sampler call
    # vs the rest of the generation loop)
    import pyabc_tpu.smc as smc_mod
    orig_sua = type(abc.sampler).sample_until_n_accepted
    tmarks = {}

    def patched_sua(self, *a, **kw):
        t0 = time.perf_counter()
        r = orig_sua(self, *a, **kw)
        tmarks["sample_until_n_accepted_s"] = round(
            time.perf_counter() - t0, 2)
        return r

    type(abc.sampler).sample_until_n_accepted = patched_sua

    # every wait in the sampler loop funnels through jax.device_get
    # (dispatch is async): time each call to decompose compute vs transfer
    get_marks = []
    orig_get = jax.device_get

    def timed_get(x):
        t0 = time.perf_counter()
        r = orig_get(x)
        leaves = jax.tree_util.tree_leaves(r)
        nbytes = sum(getattr(l, "nbytes", 8) for l in leaves)
        get_marks.append((nbytes, round(time.perf_counter() - t0, 3)))
        return r

    jax.device_get = timed_get
    t0 = time.perf_counter()
    abc.run(max_nr_populations=1)
    jax.device_get = orig_get
    res["gen2_total_s"] = round(time.perf_counter() - t0, 2)
    res.update(tmarks)
    res["gen2_nonsampling_s"] = round(
        res["gen2_total_s"] - tmarks.get("sample_until_n_accepted_s", 0), 2)
    res["device_get_marks"] = get_marks

    # per-generation wall + transfer/overlap split from the orchestrator's
    # ledger marks — in overlapped mode this is the authoritative stage
    # decomposition (compute_s = device wait before the d2h timer,
    # fetch_s = wire seconds, overlap_s = fetch hidden behind compute)
    res["generation_wall_clock_s"] = {
        t: round(v, 3) for t, v in sorted(abc.generation_wall_clock.items())}
    res["generation_transfer"] = {
        t: {k: (round(v, 4) if isinstance(v, float) else v)
            for k, v in tr.items()}
        for t, tr in sorted(abc.generation_transfer.items())}

    print(json.dumps(res, indent=1))


if __name__ == "__main__":
    main()
