"""graftlint: the unified static-analysis framework for this repo.

Public surface::

    from tools.lint import run_lint
    result = run_lint()              # all ten rules, repo defaults
    result = run_lint(rule_ids=["host-sync"])

See tools/lint/core.py for the framework, tools/lint/rules/ for the
rules, and docs/linting.md for the operator-facing catalog.
"""

from .core import (ALLOW_RE, Finding, LintResult, LintTree, RULES, Rule,
                   all_rule_ids, register, render_json, render_text,
                   run_lint)

__all__ = ["ALLOW_RE", "Finding", "LintResult", "LintTree", "RULES",
           "Rule", "all_rule_ids", "register", "render_json",
           "render_text", "run_lint"]
