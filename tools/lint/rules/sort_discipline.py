"""Rule ``sort-discipline``: no sorts inside traced hot-path modules.

A ``jnp.sort``/``jnp.argsort`` baked into a per-generation compiled
program is O(B log B) of serial-ish lane work — usually to extract ONE
order statistic (an eps quantile, a residual ranking).  PR 11 replaced
the in-scan cases with the sort-free histogram sketch
(``pyabc_tpu/ops/quantile_sketch.py``): a handful of scatter-add passes
that brackets the same statistic to ~1e-6 of the value range.  This
rule keeps the diet: a new sort in the hot path must either route
through the sketch or justify itself with an explicit allow-comment —
the surviving exact sorts (the bit-identity baseline quantile, the
sub-cap residual ranking) are annotated at the call site.

Scope: modules whose code is traced into per-generation device
programs — ``sampler/``, ``ops/``, ``weighted_statistics.py`` and
``smc.py``.  Host-side modules (epsilon/, transition fitting, ...) may
sort freely: their numpy sorts run once per generation on the host.

Suppression: ``# sort-ok`` on the line;
``# graftlint: allow(sort-discipline)`` also works.
"""

from __future__ import annotations

import os
import re
import sys

from ..core import Finding, Rule, default_package_root, register

#: traced hot-path surface (package-root-relative, forward slashes)
SCAN_PREFIXES = ("sampler/", "ops/")
SCAN_FILES = ("weighted_statistics.py", "smc.py")

SUPPRESS = "# sort-ok"

# device-array sorts: jnp./lax./jax.numpy./jax.lax. plus the
# ``xp``-dispatching idiom of weighted_statistics.py.  ``searchsorted``
# does not match (the token after the dot must BE sort/argsort).
_SORT = re.compile(
    r"\b(?:jnp|xp|lax|jax\.numpy|jax\.lax)\.(?:arg)?sort\b")


def _package_root(root: str = None) -> str:
    return root if root is not None else default_package_root()


def check(root: str = None) -> list:
    """Scan the traced surface; returns
    ``[(relpath, lineno, line), ...]`` violations (empty = clean)."""
    root = _package_root(root)
    violations = []
    for dirpath, _dirnames, filenames in os.walk(root):
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            if not (rel in SCAN_FILES or rel.startswith(SCAN_PREFIXES)):
                continue
            with open(path, encoding="utf-8") as f:
                for lineno, line in enumerate(f, 1):
                    if SUPPRESS in line:
                        continue
                    code = line.split("#", 1)[0]
                    if _SORT.search(code):
                        violations.append((rel, lineno, line.rstrip()))
    violations.sort(key=lambda v: (v[0], v[1]))
    return violations


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    root = argv[0] if argv else None
    violations = check(root)
    if not violations:
        print("sort discipline: clean (hot paths are sort-free or "
              "annotated)")
        return 0
    print("device sort in a traced hot-path module (route order "
          "statistics through ops/quantile_sketch.py, or justify the "
          f"exact sort with '{SUPPRESS}'):")
    for rel, lineno, line in violations:
        print(f"  pyabc_tpu/{rel}:{lineno}: {line.strip()}")
    return 1


@register
class SortDisciplineRule(Rule):
    id = "sort-discipline"
    description = ("traced hot-path modules use the sort-free sketch "
                   "(ops/quantile_sketch.py); exact sorts are annotated")

    def run(self, tree):
        prefix = tree.package_rel_prefix()
        return [Finding(self.id, f"{prefix}/{rel}", lineno, line.strip())
                for rel, lineno, line in check(tree.package_root)]
