"""Rule ``fused-eligibility``: the fused-chain eligibility decision
stays flag-driven and in sync with the flags' owner files.

``ABCSMC._device_chain_eligible`` decides whether a configuration's
propose→accept→refit→new-eps chain runs inside a fused device block.
That decision is deliberately NOT an isinstance whitelist: each
component family owns a capability flag (``device_accept_ok`` on
acceptors, ``device_schedule_ok``/``device_solve_ok`` on epsilon
schedules, ``device_refit_ok`` on adaptive distances,
``device_support_ok`` on transitions).  The failure mode this rule
guards against is drift: a flag renamed or dropped at its owner, or the
eligibility body quietly reverting to type checks, silently sends
eligible configs down the sequential path.

Checks:

- every capability flag is still defined in its OWNER file
  (``FLAG_OWNERS``);
- ``ABCSMC._device_chain_eligible``'s body consults every flag;
- ``ABCSMC._fused_eligible`` consults the named ``PROBE_MIN_POP``
  threshold, and neither body re-hardcodes the retired ``1 << 17``
  population cutoff;
- ``ABCSMC._onedispatch_eligible`` consults the ``device_stop_ok``
  capability flag (the device-side stop chain's extra gate).

Legacy suppression: ``# eligibility-ok`` inside the function body;
``# graftlint: allow(fused-eligibility)`` also works on line-anchored
findings.
"""

from __future__ import annotations

import ast
import os
import sys

from ..core import Finding, Rule, default_package_root, register

SUPPRESS = "# eligibility-ok"

#: capability flag -> relpath (package root) of the file that OWNS it
FLAG_OWNERS = {
    "device_accept_ok": "acceptor/acceptor.py",
    "device_schedule_ok": "epsilon/base.py",
    "device_solve_ok": "epsilon/temperature.py",
    "device_refit_ok": "distance/distance.py",
    "device_support_ok": "transition/base.py",
    "device_stop_ok": "epsilon/base.py",
}

#: flags the fused-chain body itself must consult; ``device_stop_ok``
#: is the one-dispatch path's EXTRA gate, consulted by
#: ``ONEDISPATCH_FN`` instead of the shared chain check
CHAIN_FLAGS = ("device_accept_ok", "device_schedule_ok",
               "device_solve_ok", "device_refit_ok",
               "device_support_ok")

SMC_FILE = "smc.py"
CHAIN_FN = "_device_chain_eligible"
FUSED_FN = "_fused_eligible"
ONEDISPATCH_FN = "_onedispatch_eligible"
STOP_FLAG = "device_stop_ok"
PROBE_ATTR = "PROBE_MIN_POP"
RETIRED_LITERAL = "1 << 17"


def _package_root(root: str = None) -> str:
    return root if root is not None else default_package_root()


def _function_segment(text: str, name: str):
    """(source, lineno) of def ``name`` anywhere in ``text`` (class
    methods included), or (None, 0) when absent/unparsable."""
    try:
        tree = ast.parse(text)
    except SyntaxError:
        return None, 0
    for node in ast.walk(tree):
        if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name == name):
            lines = text.splitlines()
            seg = "\n".join(lines[node.lineno - 1:node.end_lineno])
            return seg, node.lineno
    return None, 0


def check(root: str = None) -> list:
    """Returns ``[(relpath, lineno, message), ...]`` violations
    (empty = clean).  Files absent from ``root`` are skipped so
    planted-tree tests can cover subsets."""
    root = _package_root(root)
    violations = []
    for flag, rel in FLAG_OWNERS.items():
        path = os.path.join(root, rel.replace("/", os.sep))
        if not os.path.exists(path):
            continue
        with open(path, encoding="utf-8") as f:
            text = f.read()
        if flag not in text:
            violations.append((
                rel, 0,
                f"capability flag {flag!r} no longer defined in its "
                f"owner file"))
    smc_path = os.path.join(root, SMC_FILE)
    if os.path.exists(smc_path):
        with open(smc_path, encoding="utf-8") as f:
            text = f.read()
        chain_src, chain_line = _function_segment(text, CHAIN_FN)
        if chain_src is None:
            violations.append((SMC_FILE, 0,
                               f"{CHAIN_FN}() not found"))
        else:
            if SUPPRESS not in chain_src:
                for flag in CHAIN_FLAGS:
                    if flag not in chain_src:
                        violations.append((
                            SMC_FILE, chain_line,
                            f"{CHAIN_FN}() no longer consults "
                            f"{flag!r}"))
                if RETIRED_LITERAL in chain_src:
                    violations.append((
                        SMC_FILE, chain_line,
                        f"{CHAIN_FN}() hardcodes {RETIRED_LITERAL!r}; "
                        f"use the named {PROBE_ATTR} attribute"))
        fused_src, fused_line = _function_segment(text, FUSED_FN)
        if fused_src is None:
            violations.append((SMC_FILE, 0,
                               f"{FUSED_FN}() not found"))
        elif SUPPRESS not in fused_src:
            if PROBE_ATTR not in fused_src:
                violations.append((
                    SMC_FILE, fused_line,
                    f"{FUSED_FN}() no longer consults {PROBE_ATTR} "
                    f"(the at-scale engine probe threshold)"))
            if RETIRED_LITERAL in fused_src:
                violations.append((
                    SMC_FILE, fused_line,
                    f"{FUSED_FN}() hardcodes {RETIRED_LITERAL!r}; use "
                    f"the named {PROBE_ATTR} attribute"))
        one_src, one_line = _function_segment(text, ONEDISPATCH_FN)
        if one_src is None:
            violations.append((SMC_FILE, 0,
                               f"{ONEDISPATCH_FN}() not found"))
        elif SUPPRESS not in one_src:
            if STOP_FLAG not in one_src:
                violations.append((
                    SMC_FILE, one_line,
                    f"{ONEDISPATCH_FN}() no longer consults "
                    f"{STOP_FLAG!r} (the device-side stop gate)"))
            if RETIRED_LITERAL in one_src:
                violations.append((
                    SMC_FILE, one_line,
                    f"{ONEDISPATCH_FN}() hardcodes "
                    f"{RETIRED_LITERAL!r}; use the named {PROBE_ATTR} "
                    f"attribute"))
    return violations


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    root = argv[0] if argv else None
    violations = check(root)
    if not violations:
        print("fused eligibility: clean (capability flags defined at "
              "their owners and consulted by the eligibility checks)")
        return 0
    print("fused-eligibility violations (keep _device_chain_eligible "
          "flag-driven and the probe threshold named; justify with "
          f"'{SUPPRESS}'):")
    for rel, lineno, msg in violations:
        loc = f"pyabc_tpu/{rel}" + (f":{lineno}" if lineno else "")
        print(f"  {loc}: {msg}")
    return 1


@register
class FusedEligibilityRule(Rule):
    id = "fused-eligibility"
    description = ("fused-chain eligibility stays capability-flag "
                   "driven; the probe threshold stays named")

    def run(self, tree):
        prefix = tree.package_rel_prefix()
        return [Finding(self.id, f"{prefix}/{rel}", lineno, msg)
                for rel, lineno, msg in check(tree.package_root)]
