import numpy as np
from jax.experimental import multihost_utils
from jax.experimental.multihost_utils import process_allgather


def setup_barrier():
    multihost_utils.sync_global_devices(  # collective-ok: one-time mesh bring-up
        "setup")


def flush_populations(tree):
    return process_allgather(tree, tiled=True)  # collective-ok: teardown flush chokepoint


def gather_counts(local):
    return multihost_utils.process_allgather(  # graftlint: allow(collective-discipline)
        np.asarray(local))
