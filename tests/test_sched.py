"""Tier-1 gate for the elastic fleet scheduler (``pyabc_tpu/sched/``).

Pins the contracts docs/scheduling.md advertises:

- the lease mechanics: the stamp travels WITH the claim rename (zero
  invisibility window), the worker's heartbeat thread renews it, and a
  lease that stops being renewed lapses deterministically;
- scheduler reconciliation: a live (beating) worker's claims are never
  stolen however slow its study is; a heartbeat-dead worker's claims
  are reaped immediately (no lease wait) with diagnosable bounce
  breadcrumbs; a poison ticket is quarantined within its bounce budget
  with the flight dump attached;
- resume-not-restart: a requeued durable study continues from its
  journaled generation — the generation counter carries on and the
  posterior still gates — instead of restarting at generation 0;
- double-completion defense: a settled study's requeued duplicate is
  reaped at claim time, never served twice;
- autoscale hysteresis: replica targets move only after sustained
  pressure (``up_ticks``/``down_ticks``), with aging pressure and
  min/max clamps;
- observability: ``sched_*`` metrics ride the normal snapshot into
  ``fleet_rollup`` and the Prometheus exporter.

The deterministic fast subset of the ``--sched`` chaos suite
(``tools/chaos_soak.py``) runs here; the full suite (subprocess
kill -9 + journal corruption) is slow-marked.
"""

import json
import os
import sys
import time

import pytest

_REPO = os.path.abspath(os.path.join(os.path.dirname(__file__),
                                     os.pardir))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

import pyabc_tpu as pt  # noqa: E402
from pyabc_tpu.sched import Autoscaler, Scheduler  # noqa: E402
from pyabc_tpu.serve import (ServeWorker, StudyQueue,  # noqa: E402
                             StudySpec, study_digest)


def _model(key, theta):
    """Module-level (pickled through the queue, like a real tenant's
    importable model)."""
    import jax
    noise = 0.1 * jax.random.normal(key, (theta.shape[0], 1))
    return {"y": theta[:, :1] + noise}


def _spec(pop=100, seed=0, tenant="default", y=0.4, **kw):
    return StudySpec(
        model=_model,
        prior=pt.Distribution(mu=pt.RV("uniform", -1.0, 2.0)),
        observed={"y": float(y)}, population_size=pop,
        seed=seed, tenant=tenant, **kw)


def _rewind(path, by_s=3600.0):
    old = time.time() - by_s
    os.utime(path, (old, old))


def _clean_env(monkeypatch):
    for var in ("PYABC_TPU_RUN_DIR", "PYABC_TPU_SERVE_DIR",
                "PYABC_TPU_SERVE_LEASE_S",
                "PYABC_TPU_SERVE_MAX_BOUNCES"):
        monkeypatch.delenv(var, raising=False)


# ---------------------------------------------------------------------------
# lease mechanics
# ---------------------------------------------------------------------------

def test_lease_stamp_travels_with_claim(tmp_path, monkeypatch):
    """The pending file's mtime is refreshed immediately before the
    claim rename, so a stale pending ticket can never surface as an
    already-lapsed claim (the claim/crash invisibility hole)."""
    _clean_env(monkeypatch)
    q = StudyQueue(root=str(tmp_path), lease_s=60.0)
    t = q.submit(_spec(seed=1))
    _rewind(t.path)  # the ticket waited in pending for an hour
    got = q.claim("w1")
    assert got is not None and got.id == t.id
    assert q.lease_age_s(got) < 5.0, (
        "claim must re-stamp the lease: a pending-age mtime leaking "
        "into claimed/ would let the scheduler steal a fresh claim")
    assert q.lapsed() == []


def test_renew_and_lapse(tmp_path, monkeypatch):
    _clean_env(monkeypatch)
    q = StudyQueue(root=str(tmp_path), lease_s=60.0)
    q.submit(_spec(seed=2))
    got = q.claim("w1")
    _rewind(got.path)
    assert [t.id for t in q.lapsed()] == [got.id]
    # the heartbeat hook's renewal brings it back
    assert q.renew_leases("w1") == 1
    assert q.lapsed() == []
    assert q.lease_age_s(got) < 5.0


def test_heartbeat_on_beat_renews(tmp_path, monkeypatch):
    """The worker's heartbeat thread is the lease-renewal thread: one
    liveness signal, two consumers."""
    from pyabc_tpu.parallel.health import Heartbeat
    _clean_env(monkeypatch)
    q = StudyQueue(root=str(tmp_path), lease_s=60.0)
    q.submit(_spec(seed=3))
    got = q.claim("w1")
    _rewind(got.path)
    hb = Heartbeat(str(tmp_path / "run"),
                   on_beat=lambda: q.renew_leases("w1"))
    hb.beat()
    assert q.lapsed() == []
    hb.stop()


# ---------------------------------------------------------------------------
# scheduler reconciliation
# ---------------------------------------------------------------------------

def test_live_worker_never_stolen(tmp_path, monkeypatch):
    """A worker with a LIVE heartbeat keeps its claims even when the
    lease looks lapsed from the scheduler's side (e.g. an fs-cache
    hiccup delayed the renewal stamp): liveness wins."""
    _clean_env(monkeypatch)
    rd = str(tmp_path / "run")
    os.makedirs(rd)
    q = StudyQueue(root=str(tmp_path / "serve"), lease_s=60.0)
    q.submit(_spec(seed=4))
    got = q.claim("h1_42")
    _rewind(got.path)  # lease LOOKS lapsed...
    with open(os.path.join(rd, "hb_h1_42.json"), "w") as f:
        json.dump({"host": "h1", "pid": 42, "ts": time.time()}, f)
    rep = Scheduler(run_dir=rd, queue=q).tick()  # ...but hb is fresh
    assert rep["alive"] == 1 and rep["requeued"] == []
    assert q.stats()["claimed"] == 1


def test_dead_worker_fast_reap_with_breadcrumbs(tmp_path, monkeypatch):
    """A heartbeat-dead worker's claims are reaped on the next tick —
    no lease-TTL wait — and the requeued ticket carries the
    diagnosable bounce breadcrumbs."""
    _clean_env(monkeypatch)
    rd = str(tmp_path / "run")
    os.makedirs(rd)
    q = StudyQueue(root=str(tmp_path / "serve"), lease_s=3600.0)
    q.submit(_spec(seed=5))
    got = q.claim("h2_77")  # fresh lease, dead worker
    hb = os.path.join(rd, "hb_h2_77.json")
    with open(hb, "w") as f:
        json.dump({"host": "h2", "pid": 77,
                   "ts": time.time() - 900}, f)
    _rewind(hb, by_s=900.0)
    rep = Scheduler(run_dir=rd, queue=q).tick()
    assert rep["dead"] == 1 and rep["requeued"] == [got.id]
    pend = q.pending()
    assert len(pend) == 1 and pend[0].requeues == 1
    assert pend[0]._payload["last_worker"] == "h2_77"
    assert "dead" in pend[0]._payload["last_error"]
    hist = pend[0]._payload["bounce_history"]
    assert len(hist) == 1 and hist[0]["worker"] == "h2_77"


def test_poison_quarantine_within_budget(tmp_path, monkeypatch):
    """A ticket that keeps lapsing is quarantined within MAX_BOUNCES
    bounces, into a tombstone diagnosable from one file (bounce
    history + flight dump), and is never claimable again."""
    _clean_env(monkeypatch)
    q = StudyQueue(root=str(tmp_path), lease_s=60.0)
    t = q.submit(_spec(seed=6))
    sched = Scheduler(run_dir=None, queue=q, max_bounces=3)
    bounces = 0
    for i in range(10):
        got = q.claim(f"w{i}")
        if got is None:
            break
        _rewind(got.path)
        rep = sched.tick()
        bounces += 1
        if rep["quarantined"]:
            break
    assert rep["quarantined"] == [t.id]
    assert bounces <= 3, f"quarantine took {bounces} > MAX_BOUNCES"
    with open(os.path.join(q.root, "failed", f"{t.id}.json")) as f:
        tomb = json.load(f)
    assert tomb["quarantined"] is True
    assert len(tomb["bounce_history"]) == bounces - 1
    assert tomb.get("flight_path") and os.path.exists(
        tomb["flight_path"])
    assert "spec_b64" not in tomb  # tombstones stay spec-stripped
    assert q.claim("w_next") is None


def test_claim_reaps_settled_duplicate(tmp_path, monkeypatch):
    """A pending duplicate of an already-settled study (partitioned
    worker completed it after the scheduler bounced it) is reaped at
    claim time — never served twice."""
    _clean_env(monkeypatch)
    q = StudyQueue(root=str(tmp_path), lease_s=60.0)
    t = q.submit(_spec(seed=7))
    stale = q.claim("w_partitioned")
    assert Scheduler(run_dir=None, queue=q).queue is q
    _rewind(stale.path)
    Scheduler(run_dir=None, queue=q, max_bounces=99).tick()  # bounce
    assert q.stats()["pending"] == 1
    # the partition heals; the old worker completes its stale copy
    q.complete(stale, wall_s=0.1, engine="solo")
    assert q.claim("w_second") is None, "double-serve of a settled id"
    stats = q.stats()
    assert stats == {**stats, "pending": 0, "done": 1}


def test_scheduler_run_forever_max_ticks(tmp_path, monkeypatch):
    _clean_env(monkeypatch)
    q = StudyQueue(root=str(tmp_path), lease_s=60.0)
    sched = Scheduler(run_dir=None, queue=q)
    seen = []
    n = sched.run_forever(interval_s=0.01, max_ticks=2,
                          on_tick=seen.append)
    assert n == 2 and len(seen) == 2
    assert all("desired_replicas" in rep for rep in seen)


# ---------------------------------------------------------------------------
# resume-not-restart (the durable contract end to end)
# ---------------------------------------------------------------------------

def test_dead_worker_requeue_resumes_not_restarts(tmp_path, monkeypatch):
    """The acceptance path: a durable study interrupted mid-run is
    requeued by the scheduler and RESUMES from its persisted
    generation on the rescue worker — the generation counter
    continues, and the posterior still gates."""
    _clean_env(monkeypatch)
    monkeypatch.setenv("PYABC_TPU_SERVE_MULTIPLEX", "1")  # solo-only
    root = str(tmp_path / "serve")
    q = StudyQueue(root=root, lease_s=60.0)
    gens_total = 4
    spec = _spec(pop=128, seed=8, max_generations=gens_total)
    t = q.submit(spec)
    # a first worker claims it and dies mid-study: simulate by running
    # the study's first 2 generations onto the durable DB through the
    # exact engine the worker would build, then abandoning the claim
    dead = q.claim("w_dead")
    assert dead is not None
    worker = ServeWorker(root=root, worker_id="w_rescue",
                         run_mode="classic", durable=True)
    os.makedirs(worker.studies_dir, exist_ok=True)
    digest = study_digest(spec)
    db_path = os.path.join(worker.studies_dir, f"{digest}.solo.db")
    abc = worker._build_engine(spec)
    abc.new("sqlite:///" + db_path, dict(spec.observed))
    partial = abc.run(max_nr_populations=2)
    done_gens = int(partial.max_t) + 1
    assert done_gens == 2
    partial.close()
    # the worker is dead: its lease lapses and the scheduler bounces
    _rewind(dead.path)
    rep = Scheduler(run_dir=None, queue=q, max_bounces=5).tick()
    assert rep["requeued"] == [t.id]
    # the rescue worker claims the bounced ticket and must RESUME
    served = worker.run_forever(q, once=True)
    assert served == 1
    summary = worker.cache.get(f"{digest}.solo")
    assert summary is not None
    assert summary["resumed_from_gen"] == done_gens, (
        f"restarted instead of resumed: {summary}")
    assert summary["gens"] >= gens_total, (
        "the generation counter must CONTINUE across the bounce")
    # posterior gate: observed 0.4 under mu + noise, uniform prior
    assert abs(summary["posterior_mean"]["mu"] - 0.4) < 0.3
    stats = q.stats()
    assert stats["done"] == 1 and stats["pending"] == 0, (
        f"lost or duplicated study: {stats}")
    assert not os.path.exists(db_path), (
        "completed durable study must clean up its DB")


# ---------------------------------------------------------------------------
# autoscale hysteresis (pure units)
# ---------------------------------------------------------------------------

def test_autoscale_raw_target_and_clamps():
    a = Autoscaler(min_replicas=2, max_replicas=6,
                   studies_per_worker=4, aging_pressure_s=120.0)
    assert a.target(0, 0, 0.0) == 2          # min clamp
    assert a.target(8, 0, 0.0) == 2          # ceil(8/4)
    assert a.target(9, 0, 0.0) == 3          # ceil(9/4)
    assert a.target(8, 4, 0.0) == 3          # claimed counts as load
    assert a.target(8, 0, 300.0) == 3        # aging pressure adds one
    assert a.target(999, 0, 0.0) == 6        # max clamp


def test_autoscale_hysteresis_both_directions():
    a = Autoscaler(min_replicas=1, max_replicas=16,
                   studies_per_worker=1, up_ticks=2, down_ticks=3)
    assert a.observe(4, 0, 0.0) == 4         # first observation seeds
    assert a.observe(8, 0, 0.0) == 4         # up-streak 1: hold
    assert a.observe(8, 0, 0.0) == 8         # up-streak 2: move up
    assert a.observe(1, 0, 0.0) == 8         # down-streak 1: hold
    assert a.observe(1, 0, 0.0) == 8         # down-streak 2: hold
    assert a.observe(1, 0, 0.0) == 1         # down-streak 3: move down
    # a blip resets the streak: no flapping
    assert a.observe(8, 0, 0.0) == 1
    assert a.observe(1, 0, 0.0) == 1         # raw == desired: reset
    assert a.observe(8, 0, 0.0) == 1
    assert a.observe(8, 0, 0.0) == 8


# ---------------------------------------------------------------------------
# observability
# ---------------------------------------------------------------------------

def test_sched_rollup_and_prometheus(tmp_path):
    from pyabc_tpu.telemetry import aggregate
    rd = str(tmp_path)
    td = aggregate.telemetry_dir(rd)
    os.makedirs(td, exist_ok=True)
    for host, (alive, requeues) in (("hostA", (2, 3)),
                                    ("hostB", (1, 4))):
        snap = {"schema_version": aggregate.SCHEMA_VERSION,
                "host": host, "pid": 1,
                "metrics": {"sched_workers_alive": alive,
                            "sched_requeues_total": requeues,
                            "sched_desired_replicas": alive + 1}}
        with open(os.path.join(td, f"snap_{host}.json"), "w") as f:
            json.dump(snap, f)
    roll = aggregate.fleet_rollup(rd)
    sched = roll["sched"]
    # gauges take the max across scheduler replicas; counters sum
    assert sched["sched_workers_alive"] == 2
    assert sched["sched_desired_replicas"] == 3
    assert sched["sched_requeues_total"] == 7
    text = aggregate.render_prometheus(rd)
    assert "pyabc_tpu_sched_workers_alive 2" in text
    assert "pyabc_tpu_sched_requeues_total 7" in text


def test_scheduler_tick_publishes_sched_metrics(tmp_path, monkeypatch):
    _clean_env(monkeypatch)
    rd = str(tmp_path / "run")
    os.makedirs(rd)
    q = StudyQueue(root=str(tmp_path / "serve"), lease_s=60.0)
    q.submit(_spec(seed=9))
    rep = Scheduler(run_dir=rd, queue=q).tick()
    assert rep["desired_replicas"] >= 1
    from pyabc_tpu.telemetry import aggregate
    roll = aggregate.fleet_rollup(rd)
    assert roll["sched"].get("sched_queue_pending", 0) >= 1
    assert "sched_last_tick_ms" in roll["sched"]


# ---------------------------------------------------------------------------
# chaos suite: deterministic fast subset tier-1, full soak slow
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("trial", ("freeze", "poison", "shards",
                                   "trace"))
def test_sched_chaos_fast_subset(trial, tmp_path, monkeypatch):
    _clean_env(monkeypatch)
    from tools.chaos_soak import SCHED_FAST_TRIALS, run_sched_trial
    assert trial in SCHED_FAST_TRIALS
    rep = run_sched_trial(trial, str(tmp_path), seed=0)
    assert rep["lost"] == 0
    assert rep["reschedule_ms"] < 10_000
    if trial == "trace":
        # continuity across the bounce: one trace_id, both workers
        assert rep["trace_events"] >= 9


@pytest.mark.slow
def test_sched_chaos_full_soak(tmp_path, monkeypatch):
    """The whole --sched suite, subprocess kill -9 and journal
    corruption included (slow: spawns JAX child processes)."""
    _clean_env(monkeypatch)
    from tools.chaos_soak import SCHED_TRIALS, sched_soak
    reports = sched_soak(workdir=str(tmp_path), seed=0)
    assert len(reports) == len(SCHED_TRIALS)
    assert sum(r["lost"] for r in reports) == 0
