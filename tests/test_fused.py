"""Fused multi-generation blocks (sampler/fused.py; VERDICT r4 next #2).

K generations per device dispatch for configurations whose adaptation
chain is device-computable.  These tests pin: sequential-equivalent
History content (one durable row per generation), epsilon semantics
(constant and weighted-quantile annealing with host ``_look_up``
bookkeeping), posterior correctness, eligibility gating, resume, and
the simulation-budget stop inside a block.
"""

import numpy as np
import pytest

import pyabc_tpu as pt
from pyabc_tpu.models import make_two_gaussians_problem


def _abc(fuse=3, pop=400, eps=None, seed=0, **kwargs):
    models, priors, distance, observed, posterior_fn = \
        make_two_gaussians_problem()
    abc = pt.ABCSMC(models, priors, distance, population_size=pop,
                    eps=eps, sampler=pt.VectorizedSampler(),
                    fuse_generations=fuse, seed=seed, **kwargs)
    abc.new("sqlite://", observed)
    return abc, posterior_fn


def test_fused_constant_eps_history_and_posterior():
    abc, posterior_fn = _abc(fuse=3, eps=pt.ConstantEpsilon(0.2))
    h = abc.run(max_nr_populations=7)
    pops = h.get_all_populations()
    # every generation is durably present with the right epsilon
    assert list(pops.t) == [-1, 0, 1, 2, 3, 4, 5, 6]
    assert np.allclose(pops[pops.t >= 0].epsilon, 0.2)
    counts = h.get_nr_particles_per_population()
    assert all(counts[t] == 400 for t in range(7))
    probs = h.get_model_probabilities()
    assert abs(float(probs.iloc[-1][1]) - posterior_fn(1.0)) < 0.12
    # per-generation metrics exist for fused generations too
    assert set(abc.generation_wall_clock) == set(range(7))
    assert all(v > 0 for v in abc.generation_wall_clock.values())
    # weights are normalized per generation
    _, w = h.get_distribution(m=1, t=6)
    assert np.isclose(w.sum(), 1.0, atol=1e-5)


def test_fused_median_eps_anneals_and_lookup_consistent():
    abc, posterior_fn = _abc(fuse=4, seed=1)  # default MedianEpsilon
    h = abc.run(max_nr_populations=8)
    eps = h.get_all_populations()
    eps = eps[eps.t >= 0].epsilon.to_numpy()
    # weighted-median annealing: strictly decreasing, roughly halving
    assert np.all(np.diff(eps) < 0)
    assert eps[-1] < eps[1] / 8
    # the host-side schedule lookup matches the stored values (resume /
    # logging path)
    for t in range(1, len(eps)):
        assert abc.eps(t) == pytest.approx(eps[t], rel=1e-6)
    assert abs(float(h.get_model_probabilities().iloc[-1][1])
               - posterior_fn(1.0)) < 0.12


def test_fused_matches_sequential_statistically():
    """Same config, fused vs sequential: the posteriors must agree to
    Monte-Carlo noise (different RNG streams, same distribution)."""
    abc_f, _ = _abc(fuse=4, pop=600, eps=pt.ConstantEpsilon(0.15), seed=2)
    h_f = abc_f.run(max_nr_populations=6)
    abc_s, _ = _abc(fuse=1, pop=600, eps=pt.ConstantEpsilon(0.15), seed=2)
    h_s = abc_s.run(max_nr_populations=6)
    p_f = float(h_f.get_model_probabilities().iloc[-1][1])
    p_s = float(h_s.get_model_probabilities().iloc[-1][1])
    assert abs(p_f - p_s) < 0.1
    df_f, w_f = h_f.get_distribution(m=1)
    df_s, w_s = h_s.get_distribution(m=1)
    mu_f = float(df_f["mu"].to_numpy() @ w_f)
    mu_s = float(df_s["mu"].to_numpy() @ w_s)
    assert abs(mu_f - mu_s) < 0.1


def test_fused_eligibility_gating():
    # eligible: the blessed config
    abc, _ = _abc(fuse=3, eps=pt.ConstantEpsilon(0.2))
    assert abc._fused_eligible() is True
    # fuse_generations=1: off
    abc1, _ = _abc(fuse=1, eps=pt.ConstantEpsilon(0.2))
    assert abc1._fused_eligible() is False
    # adaptive distance: host consumer -> sequential
    models, priors, _, observed, _ = make_two_gaussians_problem()
    abc2 = pt.ABCSMC(models, priors, pt.AdaptivePNormDistance(),
                     population_size=200,
                     sampler=pt.VectorizedSampler(),
                     fuse_generations=3, seed=0)
    abc2.new("sqlite://", observed)
    assert abc2._fused_eligible() is False
    abc2.run(max_nr_populations=3)  # still runs, sequentially
    assert abc2.history.max_t == 2
    # sharded sampler on a single-process mesh: eligible (the
    # shard_mapped round runs inside the fused scan)
    abc3 = pt.ABCSMC(models, priors, pt.PNormDistance(p=2),
                     population_size=200,
                     sampler=pt.ShardedSampler(),
                     fuse_generations=3, seed=0)
    abc3.new("sqlite://", observed)
    assert abc3._fused_eligible() is True
    # list epsilon: not device-computable -> sequential
    abc4, _ = _abc(fuse=3, eps=pt.ListEpsilon([0.5, 0.3, 0.2, 0.1, 0.05]))
    assert abc4._fused_eligible() is False
    abc4.run(max_nr_populations=3)
    assert abc4.history.max_t == 2
    # TIME-INDEXED (but non-adaptive) distance weights: a fused block
    # would bake the t=0 weights into the compiled program — must be
    # rejected by params_time_invariant()
    models5, priors5, _, observed5, _ = make_two_gaussians_problem()
    dist5 = pt.PNormDistance(p=2, weights={0: {"y": 1.0}, 2: {"y": 5.0}})
    abc5 = pt.ABCSMC(models5, priors5, dist5, population_size=200,
                     eps=pt.ConstantEpsilon(0.5),
                     sampler=pt.VectorizedSampler(),
                     fuse_generations=3, seed=0)
    abc5.new("sqlite://", observed5)
    assert abc5._fused_eligible() is False
    abc5.run(max_nr_populations=4)  # sequential, weight switch honored
    assert abc5.history.max_t == 3
    # plain static weights stay eligible
    dist6 = pt.PNormDistance(p=2, weights={"y": 2.0})
    abc6 = pt.ABCSMC(models5, priors5, dist6, population_size=200,
                     eps=pt.ConstantEpsilon(0.5),
                     sampler=pt.VectorizedSampler(),
                     fuse_generations=3, seed=0)
    abc6.new("sqlite://", observed5)
    assert abc6._fused_eligible() is True
    # mid-size pops (>= 2^14, engages the device pdf-grid compression)
    # stay eligible; transfer-dominated huge pops fall back — measured
    # same-session, fused was ~25 % slower than sequential at 1e6
    abc7, _ = _abc(fuse=3, pop=1 << 17, eps=pt.ConstantEpsilon(0.2))
    assert abc7._fused_eligible() is True
    abc8, _ = _abc(fuse=3, pop=1_000_000, eps=pt.ConstantEpsilon(0.2))
    assert abc8._fused_eligible() is False


def test_device_grid_compression_guards():
    """Unit guards of the device pdf-grid compression: a dead model
    (no rows) yields FINITE centers with ~zero masses (never NaN), and
    an outlier-stretched range trips the bandwidth-resolution flag so
    the correction falls back to the exact support."""
    import jax.numpy as jnp

    from pyabc_tpu.sampler.fused import _compress_support_device

    n = 1 << 14
    sup = jnp.linspace(0.0, 1.0, n)[:, None]
    w = jnp.full((n,), 1.0 / n)
    ok = jnp.ones((n,), bool)
    chol = jnp.asarray([[0.01]])
    c_sup, c_lw, resolved = _compress_support_device(sup, w, ok, chol)
    assert bool(resolved)
    assert np.all(np.isfinite(np.asarray(c_sup)))
    # total mass conserved through the grid
    assert np.isclose(np.exp(np.asarray(c_lw)).sum(), 1.0, atol=1e-4)
    # one outlier at 1000 stretches the range ~1000x the bandwidth scale
    sup_out = sup.at[0, 0].set(1000.0)
    _, _, resolved_out = _compress_support_device(sup_out, w, ok, chol)
    assert not bool(resolved_out)
    # dead model: finite centers, -1e30 masses, resolved (nothing to do)
    c_sup_d, c_lw_d, resolved_d = _compress_support_device(
        sup, w, jnp.zeros((n,), bool), chol)
    assert np.all(np.isfinite(np.asarray(c_sup_d)))
    assert np.all(np.asarray(c_lw_d) <= -1e29)
    assert bool(resolved_d)


def test_fused_compressed_grid_matches_sequential():
    """At pop >= 2^14 the fused refit engages the device pdf-grid
    compression (c_support in the in-scan params); the posterior must
    still match the sequential engine (which runs the exact-support host
    fit at this per-model size)."""
    pop = 16384
    abc_f, posterior_fn = _abc(fuse=3, pop=pop,
                               eps=pt.ConstantEpsilon(0.2), seed=4)
    h_f = abc_f.run(max_nr_populations=5)
    abc_s, _ = _abc(fuse=1, pop=pop, eps=pt.ConstantEpsilon(0.2), seed=4)
    h_s = abc_s.run(max_nr_populations=5)
    p_f = float(h_f.get_model_probabilities().iloc[-1][1])
    p_s = float(h_s.get_model_probabilities().iloc[-1][1])
    # both near the analytic value and near each other (MC noise at
    # 16k particles ~ 0.01)
    assert abs(p_f - posterior_fn(1.0)) < 0.05
    assert abs(p_f - p_s) < 0.04
    df_f, w_f = h_f.get_distribution(m=1)
    df_s, w_s = h_s.get_distribution(m=1)
    mu_f = float(df_f["mu"].to_numpy() @ w_f)
    mu_s = float(df_s["mu"].to_numpy() @ w_s)
    assert abs(mu_f - mu_s) < 0.03


def test_fused_sharded_mesh():
    """Fused blocks over a ShardedSampler: the shard_mapped round runs
    inside the scan on the virtual 8-device mesh — same History shape
    and posterior as the single-device fused path."""
    models, priors, distance, observed, posterior_fn = \
        make_two_gaussians_problem()
    abc = pt.ABCSMC(models, priors, distance, population_size=400,
                    eps=pt.ConstantEpsilon(0.2),
                    sampler=pt.ShardedSampler(),
                    fuse_generations=3, seed=0)
    abc.new("sqlite://", observed)
    h = abc.run(max_nr_populations=7)
    assert list(h.get_all_populations().t) == [-1, 0, 1, 2, 3, 4, 5, 6]
    counts = h.get_nr_particles_per_population()
    assert all(counts[t] == 400 for t in range(7))
    p = float(h.get_model_probabilities().iloc[-1][1])
    assert abs(p - posterior_fn(1.0)) < 0.12


def test_fused_resume(tmp_path):
    db = f"sqlite:///{tmp_path}/fused.db"
    models, priors, distance, observed, _ = make_two_gaussians_problem()
    abc = pt.ABCSMC(models, priors, distance, population_size=300,
                    eps=pt.ConstantEpsilon(0.2),
                    sampler=pt.VectorizedSampler(),
                    fuse_generations=3, seed=0)
    abc.new(db, observed)
    abc.run(max_nr_populations=5)
    t_done = abc.history.max_t
    abc2 = pt.ABCSMC(models, priors, distance, population_size=300,
                     eps=pt.ConstantEpsilon(0.2),
                     sampler=pt.VectorizedSampler(),
                     fuse_generations=3, seed=5)
    abc2.load(db)
    abc2.run(max_nr_populations=4)
    assert abc2.history.max_t == t_done + 4
    counts = abc2.history.get_nr_particles_per_population()
    assert all(counts[t] == 300 for t in range(t_done + 5))


@pytest.mark.parametrize("cfg", [
    # (n_models, eps_kind, pop, fuse, stores_sum_stats)
    (1, "constant", 300, 3, True),
    (1, "median", 300, 3, False),
    (2, "constant", 500, 1, False),
    (2, "median", 500, 4, True),
    (3, "constant", 300, 3, False),
    (3, "median", 300, 2, True),
])
def test_config_sweep_invariants(cfg):
    """Seeded config sweep across model counts x epsilon kinds x fused/
    sequential x stats-on/off-wire: every combination must produce a
    complete History with normalized weights, full populations, finite
    thetas, and model probabilities summing to 1."""
    import jax

    from pyabc_tpu.model import SimpleModel
    from pyabc_tpu.random_variables import RV, Distribution

    n_models, eps_kind, pop, fuse, stores = cfg

    def make(shift):
        def fn(key, theta):
            return {"y": theta[:, 0] + shift
                    + 0.3 * jax.random.normal(key, theta.shape[:1])}
        return fn

    models = [SimpleModel(make(0.2 * j), name=f"m{j}")
              for j in range(n_models)]
    priors = [Distribution(mu=RV("uniform", -1.0 + 0.1 * j, 2.0))
              for j in range(n_models)]
    eps = (pt.ConstantEpsilon(0.3) if eps_kind == "constant"
           else pt.MedianEpsilon())
    abc = pt.ABCSMC(models, priors, pt.PNormDistance(p=2),
                    population_size=pop, eps=eps,
                    sampler=pt.VectorizedSampler(),
                    fuse_generations=fuse, stores_sum_stats=stores,
                    seed=7)
    abc.new("sqlite://", {"y": 0.5})
    # enough generations that a fused block actually fits AFTER the
    # sequential t=0 seeds the device carry (block entry needs
    # t + fuse <= t_max)
    gens = fuse + 2
    h = abc.run(max_nr_populations=gens)
    assert h.max_t == gens - 1
    counts = h.get_nr_particles_per_population()
    assert all(counts[t] == pop for t in range(gens))
    t_last = gens - 1
    probs = h.get_model_probabilities(t_last)
    assert np.isclose(float(np.asarray(probs).sum()), 1.0, atol=1e-4)
    for m in range(n_models):
        df, w = h.get_distribution(m=m, t=t_last)
        if len(df) == 0:
            continue
        assert np.all(np.isfinite(df["mu"].to_numpy()))
        assert np.isclose(w.sum(), 1.0, atol=1e-5)
    if eps_kind == "median":
        epses = h.get_all_populations()
        epses = epses[epses.t >= 1].epsilon.to_numpy()
        assert np.all(np.diff(epses) < 0)


def test_new_resets_fused_carry():
    """A reused ABCSMC object must not seed a NEW run's first fused
    block from the previous run's population."""
    abc, _ = _abc(fuse=3, eps=pt.ConstantEpsilon(0.2))
    abc.run(max_nr_populations=4)
    assert abc._fused_carry is not None or True  # may or may not persist
    models, priors, distance, observed, _ = make_two_gaussians_problem()
    abc.new("sqlite://", observed)
    assert abc._fused_carry is None
    h = abc.run(max_nr_populations=4)
    # the fresh run re-calibrated and started from the prior
    assert list(h.get_all_populations().t) == [-1, 0, 1, 2, 3]


def test_fused_minimum_epsilon_stop_mid_block():
    """Quantile-epsilon annealing crossing minimum_epsilon inside a
    fused block stops the run at that generation."""
    abc, _ = _abc(fuse=4, seed=2)  # MedianEpsilon
    h = abc.run(max_nr_populations=14, minimum_epsilon=0.05)
    pops = h.get_all_populations()
    eps = pops[pops.t >= 0].epsilon.to_numpy()
    assert eps[-1] <= 0.05
    assert np.all(eps[:-1] > 0.05)
    assert h.max_t < 13


def test_fused_tail_runs_sequentially():
    """When fewer than K generations remain, the block is skipped (a
    compiled block always executes K) and the tail runs sequentially —
    same History either way."""
    abc, _ = _abc(fuse=8, eps=pt.ConstantEpsilon(0.2))
    h = abc.run(max_nr_populations=4)  # 4 < K=8: no block ever fits
    assert list(h.get_all_populations().t) == [-1, 0, 1, 2, 3]
    counts = h.get_nr_particles_per_population()
    assert all(counts[t] == 400 for t in range(4))


def test_fused_undershoot_falls_back_to_sequential(caplog):
    """A fused block whose 16-round budget cannot reach n accepted
    (tight epsilon + pinned tiny batch) must truncate and hand the
    generation to the sequential path — the run still completes every
    generation with full populations."""
    import logging

    models, priors, distance, observed, _ = make_two_gaussians_problem()
    abc = pt.ABCSMC(models, priors, distance, population_size=2000,
                    eps=pt.ConstantEpsilon(0.05),
                    sampler=pt.VectorizedSampler(min_batch_size=256,
                                                 max_batch_size=256),
                    fuse_generations=2, seed=0)
    abc.new("sqlite://", observed)
    with caplog.at_level(logging.INFO, logger="ABC"):
        h = abc.run(max_nr_populations=3)
    assert h.max_t == 2
    counts = h.get_nr_particles_per_population()
    assert all(counts[t] == 2000 for t in range(3))
    # the fallback actually triggered (not silently skipped): either the
    # block undershot or never had the rounds to finish
    assert any("undershot" in r.message for r in caplog.records), \
        [r.message for r in caplog.records][-10:]


def test_fused_simulation_budget_stop():
    abc, _ = _abc(fuse=4, pop=300, eps=pt.ConstantEpsilon(0.2), seed=3)
    h = abc.run(max_nr_populations=12, max_total_nr_simulations=4000)
    pops = h.get_all_populations()
    sims = pops[pops.t >= 0].samples.to_numpy()
    # stopped once the budget tripped — well before 12 generations
    assert h.max_t < 11
    assert sims.sum() >= 4000
