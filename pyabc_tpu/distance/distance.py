"""Concrete distances: p-norms, adaptive weighting, aggregation, whitening.

Parity map to pyabc/distance/distance.py:
- ``PNormDistance``            <- :17-136  (weighted p-norm, factors)
- ``AdaptivePNormDistance``    <- :139-363 (per-generation inverse-scale
                                  weights from ALL — incl. rejected — stats)
- ``AggregatedDistance``       <- :366-511
- ``AdaptiveAggregatedDistance``<- :514-631
- ``ZScoreDistance``           <- :634-670
- ``PCADistance``              <- :673-729 (whitening)
- ``RangeEstimatorDistance``   <- :732-809
- ``MinMaxDistance``           <- :812-836
- ``PercentileDistance``       <- :839-873

TPU design: distances are pure kernels over the dense ``[N, S]`` sum-stat
block; adaptive weights are host numpy state passed in as traced params so
the compiled sampling round never recompiles across generations.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Mapping, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from .base import Distance, to_distance
from .scale import SCALE_FUNCTIONS, median_absolute_deviation
from ..ops import precision as _precision

#: jitted scale functions, weakly cached by function identity: the scale
#: math is a chain of reductions whose EAGER per-op dispatches each pay
#: the remote relay's submission constant — one fused program per
#: (fn, shape) pays it once.  Weak keys let per-instance lambdas (and
#: their compiled executables) be collected with their distance.
import weakref

_SCALE_JIT: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_SCALE_EAGER: "weakref.WeakSet" = weakref.WeakSet()


def _apply_scale(fn: Callable, *args):
    """Call ``fn`` jitted when traceable; custom callables using numpy /
    host operations (allowed by the documented contract) fall back to the
    eager call permanently.

    Exception discipline: JAX's tracer/concretization errors SUBCLASS
    TypeError, so hashability is probed separately — a blanket
    ``except TypeError`` around the jitted call would shadow the
    remember-as-eager branch and re-trace the failing fn every call.
    """
    try:
        known_eager = fn in _SCALE_EAGER
    except TypeError:
        return fn(*args)  # unhashable callable: always eager
    if known_eager:
        return fn(*args)
    jitted = _SCALE_JIT.get(fn)
    if jitted is None:
        jitted = jax.jit(fn)
        try:
            _SCALE_JIT[fn] = jitted
        except TypeError:
            pass  # unweakrefable (e.g. a ufunc): uncached jit still works
    try:
        return jitted(*args)
    except Exception:
        # not jit-traceable (numpy ops, value-dependent branching):
        # remember and run eagerly from now on
        try:
            _SCALE_EAGER.add(fn)
        except TypeError:
            pass
        return fn(*args)

Array = jnp.ndarray


class PNormDistance(Distance):
    """Weighted p-norm over sum-stat components.

    ``d(x, x0) = (Σ_s |f_s · w_s · (x_s - x0_s)|^p)^(1/p)``, ``p = inf`` ->
    max-norm.  Reference kernel math: distance/distance.py:92-103; weights
    may be time-indexed dicts ``{t: {key: w}}`` (distance/distance.py:60-78).
    """

    def __init__(self, p: float = 2.0,
                 weights: Optional[Mapping] = None,
                 factors: Optional[Mapping] = None):
        super().__init__()
        if p < 1:
            raise ValueError("p must be >= 1")
        self.p = float(p)
        # {t -> {key -> w}} or {key -> w}; resolved to per-component vectors
        # lazily once the spec is known.
        self._weights_in = weights
        self._factors_in = factors
        self.weights: Dict[int, np.ndarray] = {}
        self.factors: Optional[np.ndarray] = None

    # -- host side --------------------------------------------------------

    def _timed(self, maybe_timed) -> Dict[int, Mapping]:
        if maybe_timed is None:
            return {}
        first = next(iter(maybe_timed.values()), None)
        if isinstance(first, Mapping):
            return dict(maybe_timed)
        return {0: maybe_timed}

    def _on_bind(self, x_0):
        for tt, per_key in self._timed(self._weights_in).items():
            self.weights[tt] = self.spec.expand_key_values(per_key)
        factors = self._timed(self._factors_in)
        if factors:
            self.factors = self.spec.expand_key_values(factors[min(factors)])

    def _weights_for(self, t: int) -> np.ndarray:
        if not self.weights:
            return np.ones(self.spec.total_size, dtype=np.float32)
        # reference: use the latest generation <= t (distance.py:118-126)
        ts = [tt for tt in self.weights if tt <= t]
        tt = max(ts) if ts else min(self.weights)
        return self.weights[tt]

    def params_time_invariant(self) -> bool:
        # time-indexed {t: {key: w}} weight schedules change get_params
        # across generations even without adaptivity; the super() call
        # keeps the conservative base heuristic for USER subclasses that
        # override get_params on top of this class
        return len(self.weights) <= 1 and super().params_time_invariant()

    @property
    def device_screen_ok(self) -> bool:
        """A fixed-weight p-norm scores low- and full-fidelity stats on
        one time-invariant scale, so screening calibration pairs stay
        comparable across generations.  Time-indexed weight schedules
        (and every subclass — notably ``AdaptivePNormDistance``, whose
        per-generation refit moves the scale) stay False."""
        return (type(self) is PNormDistance
                and self.params_time_invariant())

    def get_params(self, t: int):
        w = self._weights_for(t)
        f = self.factors if self.factors is not None else np.ones_like(w)
        return {"w": jnp.asarray(w * f)}

    # -- pure kernel ------------------------------------------------------

    def compute(self, stats: Array, obs: Array, params) -> Array:
        # residual in f32 (the subtract is cancellation-sensitive); the
        # opt-in bf16 lane (ops/precision.py, PYABC_TPU_PRECISION_LANES)
        # rounds the weighted residual to bf16 — relative error 2^-8,
        # half the VPU bytes through the norm — and accumulates in f32
        diff = jnp.abs(params["w"] * (stats - obs))
        if _precision.lanes("distance") == "bf16":
            diff = diff.astype(jnp.bfloat16)
        if np.isinf(self.p):
            return jnp.max(diff, axis=-1).astype(jnp.float32)
        acc = jnp.sum(diff.astype(jnp.float32) ** self.p, axis=-1)
        return acc ** (1.0 / self.p)

    def get_config(self):
        return {"name": type(self).__name__, "p": self.p}


class AdaptivePNormDistance(PNormDistance):
    """p-norm with per-generation inverse-scale weights.

    Each generation the weights are refit as ``w_s = 1 / scale_s`` from the
    sum-stats of ALL particles (accepted and rejected) of the previous
    generation — which is why it requests rejected recording via
    ``configure_sampler`` (reference: distance/distance.py:210-224).

    ``scale_function`` contract: the recorded stats block stays
    device-resident and pads unused rows with NaN (sampler/base.py
    ``append_record_batch``), so a CUSTOM callable must be NaN-aware —
    use ``jnp.nanstd``/``jnp.nanmedian``-style reducers like the built-in
    ``SCALE_FUNCTIONS`` (distance/scale.py) do; a plain ``jnp.std`` would
    return NaN and zero out every weight.
    """

    requires_all_sum_stats = True

    def __init__(self, p: float = 2.0,
                 factors: Optional[Mapping] = None,
                 adaptive: bool = True,
                 scale_function: Union[str, Callable] = median_absolute_deviation,
                 normalize_weights: bool = True,
                 max_weight_ratio: Optional[float] = None,
                 log_file: Optional[str] = None):
        super().__init__(p=p, weights=None, factors=factors)
        self.adaptive = adaptive
        if isinstance(scale_function, str):
            scale_function = SCALE_FUNCTIONS[scale_function]
        self.scale_function = scale_function
        self.normalize_weights = normalize_weights
        self.max_weight_ratio = max_weight_ratio
        #: side-channel JSON trajectory of the per-generation weights
        #: (reference distance.py:359-363)
        self.log_file = log_file
        self._x0_flat: Optional[np.ndarray] = None

    def _on_bind(self, x_0):
        PNormDistance._on_bind(self, x_0)
        if x_0 is not None:
            self._x0_flat = np.asarray(self.spec.flatten_single(x_0))

    def initialize(self, t, get_sample_stats, x_0, spec):
        Distance.initialize(self, t, get_sample_stats, x_0, spec)
        if get_sample_stats is not None:
            self._fit(t, spec.flatten(get_sample_stats()))

    def update(self, t, get_all_stats=None) -> bool:
        if not self.adaptive or get_all_stats is None:
            return False
        if t in self.weights:
            # pre-seeded by a fused device block's in-scan refit
            # (ABCSMC._run_fused_block continuation): the schedule for t
            # is already decided — still report "changed" so population
            # distances are re-evaluated under it
            return True
        data = self.spec.flatten(get_all_stats())
        if getattr(data, "shape", (0,))[0] == 0:
            # nothing recorded (e.g. a fused continuation without a
            # record sample): keep the previous weights
            return False
        self._fit(t, data)
        return True

    @property
    def device_refit_ok(self) -> bool:
        """True when the per-generation scale refit can run INSIDE a
        fused device block (sampler/fused.py): adaptation on, a library
        scale function (traceable NaN-aware jnp reducer — a custom
        callable may use host numpy), no side-channel log file, and this
        exact class (a subclass may override ``_fit`` arbitrarily).
        Checked by ``ABCSMC._device_chain_eligible``."""
        return (type(self) is AdaptivePNormDistance
                and self.adaptive
                and self.log_file is None
                and any(self.scale_function is f
                        for f in SCALE_FUNCTIONS.values()))

    def params_time_invariant(self) -> bool:
        # adaptive refits rewrite the weight schedule every generation
        # (even when only the calibration entry exists at check time);
        # with adaptation off this is a plain time-indexed PNorm
        return (not self.adaptive) and super().params_time_invariant()

    def _fit(self, t: int, data: Array):
        """Refit weights on-device, store host-side (distance.py:268-330)."""
        scale = np.asarray(_apply_scale(
            self.scale_function, data, jnp.asarray(self._x0_flat)))
        with np.errstate(divide="ignore"):
            w = np.where(scale > 0, 1.0 / np.maximum(scale, 1e-30), 0.0)
        if self.max_weight_ratio is not None:
            pos = w[w > 0]
            if pos.size:
                w = np.minimum(w, pos.min() * self.max_weight_ratio)
        if self.normalize_weights and w.sum() > 0:
            w = w * w.size / w.sum()
        self.weights[t] = w.astype(np.float32)
        if self.log_file:
            from ..storage import save_dict_to_json
            save_dict_to_json(self.weights, self.log_file)

    def get_config(self):
        return {
            "name": type(self).__name__, "p": self.p,
            "scale_function": getattr(self.scale_function, "__name__", "custom"),
            "max_weight_ratio": self.max_weight_ratio,
        }


class AggregatedDistance(Distance):
    """Weighted sum of sub-distances (reference distance.py:366-511).

    ``d = Σ_j factor_j · w_j · d_j(x, x0)``.
    """

    def __init__(self, distances: Sequence, weights=None, factors=None):
        super().__init__()
        self.distances: List[Distance] = [to_distance(d) for d in distances]
        self.weights: Dict[int, np.ndarray] = {}
        if weights is not None:
            self.weights[0] = np.asarray(weights, dtype=np.float32)
        self.factors = (np.asarray(factors, dtype=np.float32)
                        if factors is not None
                        else np.ones(len(self.distances), dtype=np.float32))

    def bind(self, spec, x_0=None):
        super().bind(spec, x_0)
        for d in self.distances:
            d.bind(spec, x_0)

    def initialize(self, t, get_sample_stats, x_0, spec):
        super().initialize(t, get_sample_stats, x_0, spec)
        for d in self.distances:
            d.initialize(t, get_sample_stats, x_0, spec)

    def configure_sampler(self, sampler):
        super().configure_sampler(sampler)
        for d in self.distances:
            d.configure_sampler(sampler)

    def params_time_invariant(self) -> bool:
        # invariant iff every sub-distance is, no per-t weight schedule
        # is installed, and get_params has not been re-overridden by a
        # user subclass (conservative base heuristic)
        return (all(d.params_time_invariant() for d in self.distances)
                and len(self.weights) <= 1
                and Distance.params_time_invariant(self))

    def update(self, t, get_all_stats=None) -> bool:
        changed = False
        for d in self.distances:
            changed |= d.update(t, get_all_stats)
        return changed

    def _weights_for(self, t: int) -> np.ndarray:
        if not self.weights:
            return np.ones(len(self.distances), dtype=np.float32)
        ts = [tt for tt in self.weights if tt <= t]
        tt = max(ts) if ts else min(self.weights)
        return self.weights[tt]

    def get_params(self, t: int):
        return {
            "w": jnp.asarray(self._weights_for(t) * self.factors),
            "sub": tuple(d.get_params(t) for d in self.distances),
        }

    def compute(self, stats, obs, params) -> Array:
        vals = jnp.stack(
            [d.compute(stats, obs, p) for d, p in zip(self.distances, params["sub"])],
            axis=-1,
        )
        return jnp.sum(vals * params["w"], axis=-1)

    def get_config(self):
        return {"name": type(self).__name__,
                "distances": [d.get_config() for d in self.distances]}


class AdaptiveAggregatedDistance(AggregatedDistance):
    """Aggregated distance with per-generation adaptive sub-distance weights
    (reference distance.py:514-631): each generation, sub-distance values are
    computed over the previous population and weights set to inverse scale."""

    requires_all_sum_stats = True

    def __init__(self, distances, scale_function: Optional[Callable] = None,
                 adaptive: bool = True):
        super().__init__(distances)
        from .scale import span
        self.scale_function = scale_function or span
        self.adaptive = adaptive

    def _on_bind(self, x_0):
        if x_0 is not None:
            self._x0_flat = self.spec.flatten_single(x_0)

    def initialize(self, t, get_sample_stats, x_0, spec):
        super().initialize(t, get_sample_stats, x_0, spec)
        if get_sample_stats is not None:
            self._fit(t, spec.flatten(get_sample_stats()))

    def update(self, t, get_all_stats=None) -> bool:
        changed = super().update(t, get_all_stats)
        if self.adaptive and get_all_stats is not None:
            self._fit(t, self.spec.flatten(get_all_stats()))
            changed = True
        return changed

    def params_time_invariant(self) -> bool:
        # the sub-distance weights refit every generation when adaptive
        return (not self.adaptive) and super().params_time_invariant()

    def _fit(self, t: int, data: Array):
        obs = self._x0_flat
        vals = jnp.stack(
            [d.compute(data, obs, d.get_params(t)) for d in self.distances],
            axis=-1,
        )  # [N, n_dist]
        scale = np.asarray(_apply_scale(self.scale_function, vals, None))
        with np.errstate(divide="ignore"):
            w = np.where(scale > 0, 1.0 / np.maximum(scale, 1e-30), 1.0)
        self.weights[t] = w.astype(np.float32)


class ZScoreDistance(Distance):
    """Relative error: Σ |(x - x0) / x0| (reference distance.py:634-670)."""

    def compute(self, stats, obs, params) -> Array:
        denom = jnp.where(jnp.abs(obs) > 0, jnp.abs(obs), 1.0)
        rel = jnp.where(jnp.abs(obs) > 0,
                        jnp.abs((stats - obs) / denom),
                        jnp.where(jnp.abs(stats) > 0, jnp.inf, 0.0))
        return jnp.sum(rel, axis=-1)


class PCADistance(Distance):
    """Whitened euclidean distance (reference distance.py:673-729).

    Calibrates a whitening transform ``W = Λ^(-1/2) Vᵀ`` from the initial
    sample covariance; ``d = ||W (x - x0)||₂``.
    """

    def __init__(self):
        super().__init__()
        self._trafo: Optional[np.ndarray] = None

    def _on_bind(self, x_0):
        # neutral whitening until the calibration sample arrives
        self._trafo = np.eye(self.spec.total_size, dtype=np.float32)

    def initialize(self, t, get_sample_stats, x_0, spec):
        super().initialize(t, get_sample_stats, x_0, spec)
        if get_sample_stats is None:
            return
        data = np.asarray(spec.flatten(get_sample_stats()))
        cov = np.cov(data, rowvar=False)
        cov = np.atleast_2d(cov) + 1e-8 * np.eye(data.shape[1])
        evals, evecs = np.linalg.eigh(cov)
        evals = np.maximum(evals, 1e-12)
        self._trafo = (evecs / np.sqrt(evals)).T.astype(np.float32)

    def get_params(self, t):
        return {"W": jnp.asarray(self._trafo)}

    def compute(self, stats, obs, params) -> Array:
        z = jnp.matmul(stats - obs, params["W"].T,
                       precision=jax.lax.Precision.HIGHEST)
        return jnp.sqrt(jnp.sum(z**2, axis=-1))


class DistanceWithMeasureList(PNormDistance):
    """Base for distances over a subset of summary statistics
    (reference distance.py:634-706): ``measures_to_use`` selects which
    sum-stat keys enter the distance ("all" or a list of key names);
    unused keys get weight 0 in the dense block."""

    def __init__(self, measures_to_use="all", p: float = 2.0):
        super().__init__(p=p)
        self.measures_to_use = measures_to_use

    def _measure_mask(self) -> np.ndarray:
        """Per-component 0/1 mask over the flat block from the key list."""
        if self.measures_to_use == "all":
            return np.ones(self.spec.total_size, dtype=np.float32)
        return self.spec.expand_key_values(
            {k: 1.0 for k in self.measures_to_use}, default=0.0)

    def get_params(self, t):
        params = super().get_params(t)
        params["w"] = params["w"] * jnp.asarray(self._measure_mask())
        return params

    def get_config(self):
        cfg = super().get_config()
        cfg["measures_to_use"] = (self.measures_to_use
                                  if self.measures_to_use == "all"
                                  else list(self.measures_to_use))
        return cfg


class RangeEstimatorDistance(DistanceWithMeasureList):
    """p-norm normalized by a calibrated per-component range
    (reference distance.py:732-809, subclassing the measure-list base as
    the reference does): the range's inverse IS the p-norm weight vector,
    so the kernel is inherited from :class:`PNormDistance`.  Subclasses
    define ``lower``/``upper`` over the calibration sample."""

    def __init__(self, measures_to_use="all", p: float = 2.0):
        super().__init__(measures_to_use=measures_to_use, p=p)
        self._inv_range: Optional[np.ndarray] = None

    @staticmethod
    def lower(data: np.ndarray) -> np.ndarray:
        return np.min(data, axis=0)

    @staticmethod
    def upper(data: np.ndarray) -> np.ndarray:
        return np.max(data, axis=0)

    def _on_bind(self, x_0):
        super()._on_bind(x_0)
        self._inv_range = np.ones(self.spec.total_size, dtype=np.float32)

    def initialize(self, t, get_sample_stats, x_0, spec):
        super().initialize(t, get_sample_stats, x_0, spec)
        if get_sample_stats is None:
            return
        data = np.asarray(spec.flatten(get_sample_stats()))
        rng = self.upper(data) - self.lower(data)
        with np.errstate(divide="ignore"):
            self._inv_range = np.where(rng > 0, 1.0 / np.maximum(rng, 1e-30),
                                       0.0).astype(np.float32)

    def get_params(self, t):
        return {"w": jnp.asarray(self._inv_range * self._measure_mask())}


class MinMaxDistance(RangeEstimatorDistance):
    """Range = max - min (reference distance.py:812-836)."""


class PercentileDistance(RangeEstimatorDistance):
    """Range between percentiles (reference distance.py:839-873)."""

    PERCENTILE = 10

    @classmethod
    def lower(cls, data):
        return np.percentile(data, cls.PERCENTILE, axis=0)

    @classmethod
    def upper(cls, data):
        return np.percentile(data, 100 - cls.PERCENTILE, axis=0)
