"""ABC-as-a-service: multi-tenant study serving on warm workers.

The serving tier turns "a fast run" (one :class:`~pyabc_tpu.ABCSMC`
driving one study) into "a service": many small studies from many
tenants multiplexed onto a persistent worker that keeps its compiled
programs warm across studies.  Five pieces:

- :mod:`pyabc_tpu.serve.spec` — the study spec (prior + model +
  distance + eps config + observed data) and its canonical
  content-address digest;
- :mod:`pyabc_tpu.serve.queue` — the admission queue over the
  ``parallel/`` mount contract, with per-tenant quotas, backpressure
  and priority aging;
- :mod:`pyabc_tpu.serve.shards` — the partitioned queue layout:
  ``pending/`` sharded by ``hash(digest) % PYABC_TPU_SERVE_PARTITIONS``
  so claim scans and rename contention are O(depth/P);
- :mod:`pyabc_tpu.serve.cache` — the two-tier content-addressed study
  cache (worker LRU in front of a shared CRC-verified store) serving
  any worker's duplicate submissions without a dispatch;
- :mod:`pyabc_tpu.serve.admission` — SLO load-shedding: reject-fast
  with a computed ``retry_after_s`` when partition depth or the
  fleet's served p99 breach the configured SLO knobs
  (``PYABC_TPU_SERVE_SLO_DEPTH``, ``PYABC_TPU_SERVE_SLO_P99_MS``);
- :mod:`pyabc_tpu.serve.multiplex` — the study axis: N small studies
  vmapped into ONE fused program with per-study live-sentinel masking,
  dispatched in re-entrant windows so lanes retire/join continuously
  (``PYABC_TPU_SERVE_CB*``);
- :mod:`pyabc_tpu.serve.worker` — the persistent warm worker
  (``abc-serve``) pinning the AOT :class:`CompiledLadder` across
  studies and routing eligible ones through ``run_mode="onedispatch"``.

All serving knobs are serve-prefixed environment variables,
documented in ``docs/serving.md``.
"""

from .admission import AdmissionController, ServeOverloaded
from .cache import SharedResultStore, StudyCache, TieredStudyCache
from .multiplex import (ShapeHysteresis, StudyBatch, lane_eligible,
                        multiplex_eligible)
from .queue import (QueueFull, SpecAuthError, StudyQueue,
                    TenantQuotaExceeded)
from .spec import StudySpec, problem_key, study_digest
from .worker import ServeWorker

__all__ = [
    "AdmissionController",
    "QueueFull",
    "ServeOverloaded",
    "ServeWorker",
    "ShapeHysteresis",
    "SharedResultStore",
    "SpecAuthError",
    "StudyBatch",
    "StudyCache",
    "StudyQueue",
    "StudySpec",
    "TenantQuotaExceeded",
    "TieredStudyCache",
    "lane_eligible",
    "multiplex_eligible",
    "problem_key",
    "study_digest",
]
