"""Population-size strategies.

Parity: pyabc/populationstrategy.py (261 LoC): constant / per-generation
list / adaptive population size, the adaptive variant using bootstrap CV of
the KDE fits + power-law extrapolation to hit a target coefficient of
variation (populationstrategy.py:132-227).

TPU note: changing N between generations changes compiled shapes (one
recompile per change).  ``AdaptivePopulationSize`` therefore quantizes the
predicted size to powers of two by default (``quantize=True``) so at most a
handful of round shapes are ever compiled.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import numpy as np

from .cv.bootstrap import calc_cv


class PopulationStrategy:
    """Base (reference populationstrategy.py:24-95)."""

    def __init__(self, nr_particles: int, nr_samples_per_parameter: int = 1):
        self.nr_particles = int(nr_particles)
        self.nr_samples_per_parameter = int(nr_samples_per_parameter)

    def update(self, transitions: List, model_weights, t: Optional[int] = None,
               test_points_per_model: Optional[List] = None):
        pass

    def __call__(self, t: Optional[int] = None) -> int:
        return self.nr_particles

    def get_config(self) -> dict:
        return {"name": type(self).__name__, "nr_particles": self.nr_particles}

    def to_json(self) -> str:
        import json
        return json.dumps(self.get_config())


class ConstantPopulationSize(PopulationStrategy):
    """Fixed N (reference populationstrategy.py:98-129)."""


class ListPopulationSize(PopulationStrategy):
    """Per-generation sizes (reference populationstrategy.py:230-261)."""

    def __init__(self, values: List[int], nr_samples_per_parameter: int = 1):
        super().__init__(values[0], nr_samples_per_parameter)
        self.values = [int(v) for v in values]

    def __call__(self, t: Optional[int] = None) -> int:
        if t is None:
            return self.values[0]
        return self.values[min(t, len(self.values) - 1)]


class AdaptivePopulationSize(PopulationStrategy):
    """CV-targeted adaptive N (reference populationstrategy.py:132-227)."""

    def __init__(self, start_nr_particles: int,
                 mean_cv: float = 0.05,
                 max_population_size: int = 10**6,
                 min_population_size: int = 10,
                 n_bootstrap: int = 5,
                 quantize: bool = True,
                 seed: int = 0):
        super().__init__(start_nr_particles)
        self.mean_cv = float(mean_cv)
        self.max_population_size = int(max_population_size)
        self.min_population_size = int(min_population_size)
        self.n_bootstrap = int(n_bootstrap)
        self.quantize = quantize
        self._key = jax.random.PRNGKey(seed)

    def update(self, transitions: List, model_weights, t=None,
               test_points_per_model: Optional[List] = None):
        """Multi-size bootstrap + power-law inversion (reference
        populationstrategy.py:203-222 via
        transition/predict_population_size.py:11-60): estimate the KDE CV
        at three population sizes around the current one, fit
        ``cv(n) = a·n^b`` and invert at the target CV."""
        from .transition.predict_population_size import \
            predict_population_size

        if test_points_per_model is None:
            test_points_per_model = [tr.theta for tr in transitions]
        reference_nr = self.nr_particles
        sizes = sorted({
            int(max(reference_nr // 2, self.min_population_size, 8)),
            int(reference_nr),
            int(min(reference_nr * 2, self.max_population_size)),
        })
        cvs = {}
        for nn in sizes:
            self._key, sub = jax.random.split(self._key)
            cv_n, _ = calc_cv(nn, model_weights, transitions,
                              self.n_bootstrap, test_points_per_model,
                              key=sub)
            if cv_n > 0:
                cvs[nn] = float(cv_n)
        if not cvs:
            return
        n_req = predict_population_size(
            cvs, self.mean_cv, min_size=self.min_population_size,
            max_size=self.max_population_size, fallback=reference_nr)
        if self.quantize:
            n_req = 1 << int(np.ceil(np.log2(max(n_req, 2))))
            n_req = min(n_req, self.max_population_size)
        self.nr_particles = int(n_req)

    def get_config(self):
        return {"name": type(self).__name__,
                "max_population_size": self.max_population_size,
                "mean_cv": self.mean_cv}
