"""graftlint rule modules — importing this package registers all
eighteen rules with :data:`tools.lint.core.RULES` (registration order
is the default run order: the six ported gates first, then the new
analyzers)."""

from . import wire_chokepoint    # noqa: F401
from . import no_inline_jit      # noqa: F401
from . import retry_sites        # noqa: F401
from . import fused_eligibility  # noqa: F401
from . import span_pairs         # noqa: F401
from . import fault_sites        # noqa: F401
from . import host_sync          # noqa: F401
from . import lock_discipline    # noqa: F401
from . import prng_keys          # noqa: F401
from . import env_drift          # noqa: F401
from . import sort_discipline    # noqa: F401
from . import precision_policy   # noqa: F401
from . import collective_discipline  # noqa: F401
from . import study_isolation    # noqa: F401
from . import claim_discipline   # noqa: F401
from . import event_discipline   # noqa: F401
from . import fidelity_discipline  # noqa: F401
from . import pop_materialization  # noqa: F401
