import jax.numpy as jnp


def cross(zq, zb):
    return jnp.matmul(zq, zb.T)


def center(w, support):
    return w @ support


def logits(a, b):
    return jnp.dot(a, b)
